"""The engine × axis contract matrix: enumerate, check, report.

``ENGINE_CAPS`` declares each engine's structural contract
(``analysis.contracts``); this module sweeps the full cross-product —
engine × {single, sharded, batched, guarded, abft, storage, history} —
on a tiny grid, entirely by abstract tracing (no solver compiles), and
emits a deterministic machine-readable report: JSON, SARIF, and a
classified exit code mirroring tpulint's (0 clean, 1 violations,
2 a cell errored out).

Cells are suppressible with a reason, tpulint-style, via
``[tool.engine_contracts] suppress`` in ``pyproject.toml``::

    suppress = ["pipelined:sharded:collective-cadence: known drift, #123"]

A suppressed failing cell reads as suppressed (exit stays 0); a
suppression that no longer matches a failing cell is reported unused —
the same accept-then-ratchet hygiene the linter applies to its
``disable`` comments.
"""

from __future__ import annotations

import hashlib
import json
import re
from typing import Optional

from poisson_ellipse_tpu.analysis import contracts

TOOL_NAME = "engine-contracts"
REPORT_VERSION = 1

# axis -> the contract kinds that can run there (applicability per
# engine is the capability row's business — contracts.contract_applies)
AXIS_CONTRACTS = {
    "single": ("single-collective-free",),
    "sharded": ("collective-cadence", "fcycle-budget"),
    "batched": ("batched-cadence",),
    "guarded": ("guard-overhead",),
    "abft": ("abft-identity",),
    "storage": ("storage-identity", "storage-narrow"),
    "history": ("history-free", "history-resident"),
    "fleet": ("fleet-chaos",),
    "recycle": ("recycle-deflation",),
}
AXES = tuple(AXIS_CONTRACTS)

_SUPPRESS_RE = re.compile(
    r"^\s*([^:\s]+)\s*:\s*([^:\s]+)\s*:\s*([^:\s]+)\s*(?::\s*(.*))?$"
)


def cell_id(engine: str, axis: str, kind: str) -> str:
    return f"{engine}:{axis}:{kind}"


def enumerate_cells(
    engines: Optional[tuple[str, ...]] = None,
    axes: Optional[tuple[str, ...]] = None,
) -> list[tuple[str, str, str]]:
    """Every applicable (engine, axis, kind) cell, sorted — the
    deterministic sweep order every report uses."""
    from poisson_ellipse_tpu.solver.engine import ENGINE_CAPS

    engines = tuple(engines) if engines else tuple(ENGINE_CAPS)
    axes = tuple(axes) if axes else AXES
    cells = []
    for engine in engines:
        for axis in axes:
            for kind in AXIS_CONTRACTS[axis]:
                try:
                    applies = contracts.contract_applies(kind, engine)
                except ValueError:
                    # missing/malformed metadata: the engine-metadata
                    # check below names it; no per-axis cells to run
                    applies = False
                if applies:
                    cells.append((engine, axis, kind))
    return sorted(cells)


def load_suppressions(root: Optional[str] = None) -> dict[str, str]:
    """``[tool.engine_contracts] suppress`` entries -> {cell id: reason}.

    Reuses the tpulint pyproject reader (tomllib with the flat-array
    subset fallback), so the knob parses identically everywhere.
    """
    import os

    from poisson_ellipse_tpu.lint import _read_pyproject

    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    pyproject = os.path.join(root, "pyproject.toml")
    if not os.path.exists(pyproject):
        return {}
    table = _read_pyproject(pyproject).get("tool", {}).get(
        "engine_contracts", {}
    )
    out: dict[str, str] = {}
    for entry in table.get("suppress", []):
        m = _SUPPRESS_RE.match(str(entry))
        if not m:
            raise SystemExit(
                f"[tool.engine_contracts] suppress entry {entry!r} is not "
                "'engine:axis:kind: reason'"
            )
        engine, axis, kind, reason = m.groups()
        out[cell_id(engine, axis, kind)] = reason or "(no reason given)"
    return out


def _jsonable(value):
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return str(value)


def run_matrix(
    engines: Optional[tuple[str, ...]] = None,
    axes: Optional[tuple[str, ...]] = None,
    *,
    problem=None,
    mesh_shape: tuple[int, int] = (1, 2),
    suppressions: Optional[dict[str, str]] = None,
) -> dict:
    """Sweep the matrix; return the deterministic report dict.

    ``suppressions`` defaults to the pyproject table; pass ``{}`` to run
    unsuppressed (the pytest gate does, so a suppression can never hide
    a regression from tier-1 silently).
    """
    if suppressions is None:
        suppressions = load_suppressions()
    cells = enumerate_cells(engines, axes)
    rows: list[dict] = []
    n_pass = n_fail = n_suppressed = n_error = 0
    violations: list[str] = []
    used: set[str] = set()

    # the registration gate runs once, ahead of the per-cell sweep
    meta = contracts.check_engine_metadata()
    meta_row = {
        "engine": "*",
        "axis": "registry",
        "kind": "engine-metadata",
        "status": "fail" if meta else "pass",
        "expected": {"declared": True},
        "actual": {"missing": [v.engine for v in meta]},
        "messages": [v.message for v in meta],
    }
    if meta:
        n_fail += 1
        violations.extend(v.render() for v in meta)
    else:
        n_pass += 1
    rows.append(meta_row)

    for engine, axis, kind in cells:
        cid = cell_id(engine, axis, kind)
        try:
            result = contracts.check_contract(
                kind, engine, problem=problem, mesh_shape=mesh_shape
            )
            row = {
                "engine": engine,
                "axis": axis,
                "kind": kind,
                "status": result.status,
                "expected": _jsonable(result.expected),
                "actual": _jsonable(result.actual),
                "messages": [v.message for v in result.violations],
            }
        # a crashed cell is CLASSIFIED, not swallowed: status "error"
        # carries the exception name in messages and trumps the exit
        # code (2) — the deliberate-swallow shape TPU009 fences allows
        # tpulint: disable=TPU009
        except Exception as e:  # a cell that cannot run is exit 2, not 0
            row = {
                "engine": engine,
                "axis": axis,
                "kind": kind,
                "status": "error",
                "expected": None,
                "actual": None,
                "messages": [f"{type(e).__name__}: {e}"],
            }
        if row["status"] == "fail" and cid in suppressions:
            row["status"] = "suppressed"
            row["suppressed_reason"] = suppressions[cid]
            used.add(cid)
            n_suppressed += 1
        elif row["status"] == "fail":
            n_fail += 1
            violations.extend(
                f"{cid}: {m}" for m in row["messages"]
            )
        elif row["status"] == "error":
            n_error += 1
            violations.extend(f"{cid}: {m}" for m in row["messages"])
        else:
            n_pass += 1
        rows.append(row)

    unused = sorted(set(suppressions) - used)
    report = {
        "tool": TOOL_NAME,
        "version": REPORT_VERSION,
        "grid": (
            [problem.M, problem.N] if problem is not None else [16, 16]
        ),
        "mesh": list(mesh_shape),
        "cells": rows,
        "summary": {
            "checked": len(rows),
            "pass": n_pass,
            "fail": n_fail,
            "error": n_error,
            "suppressed": n_suppressed,
        },
        "violations": violations,
        "unused_suppressions": unused,
        "clean": n_fail == 0 and n_error == 0,
    }
    return report


def report_hash(report: dict) -> str:
    """The canonical-JSON sha256 of a matrix report — what a bench round
    embeds so two perf numbers are only compared under the same (clean)
    contract state."""
    return hashlib.sha256(
        json.dumps(report, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()


def exit_code(report: dict) -> int:
    """0 clean (incl. suppressed), 1 contract violations, 2 a cell
    errored (unusable sweep trumps findings — mirror tpulint)."""
    if report["summary"]["error"]:
        return 2
    return 1 if report["summary"]["fail"] else 0


def render_report(report: dict) -> str:
    """Human-readable matrix summary: one line per non-pass cell plus
    the tally (the CLI's default text form)."""
    lines = [
        f"{TOOL_NAME}: grid {report['grid'][0]}x{report['grid'][1]}, "
        f"mesh {report['mesh'][0]}x{report['mesh'][1]}, "
        f"{report['summary']['checked']} contract cells"
    ]
    for row in report["cells"]:
        if row["status"] == "pass":
            continue
        cid = cell_id(row["engine"], row["axis"], row["kind"])
        if row["status"] == "suppressed":
            lines.append(
                f"  suppressed {cid}: {row['suppressed_reason']}"
            )
        else:
            for msg in row["messages"]:
                lines.append(f"  {row['status'].upper()} {cid}: {msg}")
    for cid in report["unused_suppressions"]:
        lines.append(f"  unused suppression: {cid}")
    s = report["summary"]
    lines.append(
        f"  {s['pass']} pass, {s['fail']} fail, {s['error']} error, "
        f"{s['suppressed']} suppressed — "
        + ("clean" if report["clean"] else "NOT clean")
    )
    return "\n".join(lines)


def report_to_sarif(report: dict) -> dict:
    """Matrix report -> SARIF (the shared writer; one result per
    non-pass cell, ruleId = the contract kind)."""
    from poisson_ellipse_tpu.analysis.sarif import sarif_report, sarif_result

    results = []
    for row in report["cells"]:
        if row["status"] == "pass":
            continue
        cid = cell_id(row["engine"], row["axis"], row["kind"])
        level = {
            "fail": "error", "error": "error", "suppressed": "note"
        }[row["status"]]
        for msg in row["messages"] or [row.get("suppressed_reason", "")]:
            results.append(
                sarif_result(row["kind"], f"{cid}: {msg}", level=level)
            )
    return sarif_report(
        TOOL_NAME,
        results,
        rules=dict(contracts.CONTRACT_KINDS),
    )
