"""analysis/ — whole-matrix static contract checking over jaxprs.

Three layers, one import rule:

- :mod:`.jaxpr_scan` — the traversal engine (``make_jaxpr``-based, no
  compiles, no devices); ``obs.static_cost`` consumes it too.
- :mod:`.contracts` — the declarative contract schema; expected values
  derive from ``solver.engine.ENGINE_CAPS``'s per-row ``contracts``
  metadata. Tests call ``assert_contract(...)``.
- :mod:`.matrix` — the engine × axis sweep, JSON/SARIF reports, and the
  classified exit contract (``python -m poisson_ellipse_tpu.analysis``).

This package ``__init__`` stays import-light on purpose: :mod:`.sarif`
is pure stdlib and is imported by the tpulint CLI, which must never pull
in JAX — reach the JAX-facing modules by their full names.
"""

from __future__ import annotations

__all__ = ["assert_contract", "check_contract", "run_matrix"]


def __getattr__(name: str):
    # lazy: keep `import poisson_ellipse_tpu.analysis.sarif` (the lint
    # CLI's path) from importing jax via the contract machinery
    if name in ("assert_contract", "check_contract"):
        from poisson_ellipse_tpu.analysis import contracts

        return getattr(contracts, name)
    if name == "run_matrix":
        from poisson_ellipse_tpu.analysis import matrix

        return matrix.run_matrix
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
