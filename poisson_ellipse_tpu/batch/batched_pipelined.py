"""Batched pipelined PCG: B lanes, one stacked (8, B) dot bundle/iter.

The Ghysels–Vanroose recurrence (``ops.pipelined_pcg``) widened by a
lane axis: every inner product of an iteration is a function of vectors
already in hand, so the whole batch's bundle — 8 dots × B lanes — rides
ONE stacked reduction, and the iteration's stencil applications have no
data dependence on it. That is the property that keeps the lane-sharded
mesh composition at exactly **one psum per iteration regardless of B**
(``parallel.batched_sharded``): per-lane bundles need no collective at
all (lanes live whole on their device), and the single psum that
synchronises the loop is independent of the lane count.

Per-lane semantics are ``ops.pipelined_pcg``'s: the expanded
α-denominator (not the cancellation-prone scalar recursion), breakdown
under ``DENOM_GUARD`` discarding the iteration's update, fixed-cadence
residual replacement every ``REPLACE_EVERY`` iterations (keyed on the
global counter, so chunked runs stay bit-identical), and the ±2-of-
classical iteration-count contract. Lane freezing, in-loop quarantine
and the bucket-embedding mask follow ``batch.batched_pcg``.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from poisson_ellipse_tpu.batch.batched_pcg import (
    BatchedPCGResult,
    _lane_ops,
    apply_a_batched,
    apply_dinv_batched,
    diag_d_batched,
    lane_dots,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.pipelined_pcg import REPLACE_EVERY
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD


def _bundle(r, u, w, s, p):
    """The iteration's eight dot pairs per lane, in
    ``ops.pipelined_pcg._bundle`` order: γ, the four α-denominator
    terms, the three ‖Δx‖-recurrence terms."""
    return (
        (r, u),
        (w, u), (w, p), (s, u), (s, p),
        (u, u), (u, p), (p, p),
    )


def _stencil_closure(a3, b3, m3, h1, h2, stencil, interpret, hs):
    """The per-lane A·(·) closure: "xla" broadcasts through
    ``apply_a_batched``; "pallas" runs the lane-on-grid batched kernel
    (lane-shared coefficients, concrete ``hs`` baked in)."""
    if stencil == "pallas":
        from poisson_ellipse_tpu.ops.pallas_kernels import (
            apply_a_batched_pallas,
        )

        if a3.shape[0] != 1 or b3.shape[0] != 1:
            raise ValueError(
                "the batched Pallas stencil streams lane-shared "
                "coefficients; per-lane (B, g1, g2) a/b need stencil='xla'"
            )

        def fn(v):
            out = apply_a_batched_pallas(
                v, a3[0], b3[0], hs[0], hs[1], interpret=interpret
            )
            return out if m3 is None else out * m3

        return fn
    if stencil != "xla":
        raise ValueError(f"unknown stencil: {stencil!r}")

    def fn(v):
        out = apply_a_batched(v, a3, b3, h1, h2)
        return out if m3 is None else out * m3

    return fn


def init_state(problem: Problem, a, b, rhs, mask=None, h1=None, h2=None,
               stencil: str = "xla", interpret=None):
    """The batched pipelined carry at iteration 0: (k, x, r, u, w, z, s,
    p, γ₋₁, diff, converged, breakdown, quarantined, iters) with (B,)
    per-lane scalars/flags."""
    dtype = rhs.dtype
    B = rhs.shape[0]
    h1 = jnp.asarray(problem.h1 if h1 is None else h1, dtype)
    h2 = jnp.asarray(problem.h2 if h2 is None else h2, dtype)
    a3, b3, m3 = _lane_ops(a, b, mask)
    d = diag_d_batched(a3, b3, h1, h2, m3)
    stencil = _stencil_closure(
        a3, b3, m3, h1, h2, stencil, interpret, (problem.h1, problem.h2)
    )

    r0 = rhs
    u0 = apply_dinv_batched(r0, d)
    w0 = stencil(u0)
    zeros = jnp.zeros_like(rhs)
    return (
        jnp.asarray(0, jnp.int32),
        zeros,  # x
        r0,
        u0,
        w0,
        zeros,  # z
        zeros,  # s
        zeros,  # p
        jnp.ones((B,), dtype),          # γ of the previous iteration
        jnp.full((B,), jnp.inf, dtype),
        jnp.zeros((B,), bool),
        jnp.zeros((B,), bool),
        jnp.zeros((B,), bool),
        jnp.zeros((B,), jnp.int32),
    )


def advance(problem: Problem, a, b, rhs, state, limit=None, mask=None,
            h1=None, h2=None, delta=None, stencil: str = "xla",
            interpret=None):
    """Advance the batched pipelined carry until every lane is done or
    iteration ``limit``. Same traced-scalar/bucket-mask contract as
    ``batch.batched_pcg.advance``; chunked runs are bit-identical to a
    straight run (residual replacement keys on the global counter).
    ``stencil="pallas"`` fuses each iteration's stencil with its whole
    (8, B) dot bundle in one kernel launch
    (``ops.pallas_kernels.apply_a_dots_batched_pallas``)."""
    if stencil == "pallas" and (h1 is not None or h2 is not None):
        raise ValueError(
            "the batched Pallas kernels bake h1/h2 in as compile-time "
            "constants; traced overrides need stencil='xla'"
        )
    dtype = rhs.dtype
    h1 = jnp.asarray(problem.h1 if h1 is None else h1, dtype)
    h2 = jnp.asarray(problem.h2 if h2 is None else h2, dtype)
    delta = jnp.asarray(problem.delta if delta is None else delta, dtype)
    max_iter = (
        problem.max_iterations
        if limit is None
        else jnp.minimum(
            jnp.asarray(limit, jnp.int32), problem.max_iterations
        )
    )
    weighted = problem.norm == "weighted"
    a3, b3, m3 = _lane_ops(a, b, mask)
    d = diag_d_batched(a3, b3, h1, h2, m3)
    body = make_lane_step(rhs, a3, b3, d, m3, h1, h2, delta, weighted,
                          stencil=stencil, interpret=interpret,
                          hs=(problem.h1, problem.h2))

    def cond(state):
        k, conv, bd, quar = state[0], state[10], state[11], state[12]
        return (k < max_iter) & jnp.any(~conv & ~bd & ~quar)

    return lax.while_loop(cond, body, state)


def make_lane_step(rhs, a3, b3, d, m3, h1, h2, delta, weighted,
                   stencil: str = "xla", interpret=None, hs=None):
    """One batched-pipelined iteration as a carry→carry function —
    factored for the lane-sharded composition, exactly like
    ``batched_pcg.make_lane_step``. ``stencil="pallas"`` streams the
    iteration's stencil AND its (8, B) bundle through the fused
    lane-on-grid kernel in one VMEM pass."""
    hw = h1 * h2
    pallas = stencil == "pallas"
    stencil = _stencil_closure(a3, b3, m3, h1, h2, stencil, interpret, hs)

    if pallas:
        from poisson_ellipse_tpu.ops.pallas_kernels import (
            apply_a_dots_batched_pallas,
        )

        def stencil_and_dots(m, r, u, w, s, p):
            # one launch: n = A·m AND the eight per-lane dot partials,
            # every operand read from HBM exactly once
            n, sums = apply_a_dots_batched_pallas(
                m, a3[0], b3[0], hs[0], hs[1], _bundle(r, u, w, s, p),
                interpret=interpret,
            )
            return (n if m3 is None else n * m3), sums

    else:

        def stencil_and_dots(m, r, u, w, s, p):
            return stencil(m), lane_dots(*_bundle(r, u, w, s, p))

    def replace(k, x, r, u, w, z, s, p):
        """Residual replacement from ground-truth x and p (4 stencils),
        fixed cadence, all lanes at once."""

        def rebuilt(_):
            r_t = rhs - stencil(x)
            u_t = apply_dinv_batched(r_t, d)
            s_t = stencil(p)
            return (
                r_t, u_t, stencil(u_t),
                stencil(apply_dinv_batched(s_t, d)), s_t,
            )

        do = (k > 0) & (k % REPLACE_EVERY == 0)
        return lax.cond(do, rebuilt, lambda _: (r, u, w, z, s), None)

    def body(state):
        (k, x, r, u, w, z, s, p, g_prev, diff_prev,
         conv, bd, quar, iters) = state
        active = ~conv & ~bd & ~quar
        r, u, w, z, s = replace(k, x, r, u, w, z, s, p)

        # the iteration's ONE stacked (8, B) reduction; the stencil
        # consumes none of it (the overlap property the sharded
        # composition relies on) — under "pallas" both ride one fused
        # kernel launch
        m = apply_dinv_batched(w, d)
        n, sums = stencil_and_dots(m, r, u, w, s, p)

        gamma = sums[0] * hw
        wu, wp, su, sp = sums[1], sums[2], sums[3], sums[4]
        uu, up, pp = sums[5], sums[6], sums[7]

        first = k == 0
        beta = jnp.where(first, 0.0, gamma / jnp.where(first, 1.0, g_prev))
        denom = (wu + beta * (wp + su) + beta * beta * sp) * hw
        breakdown = denom < DENOM_GUARD
        alpha = gamma / jnp.where(breakdown, 1.0, denom)

        be = beta[:, None, None]
        al = alpha[:, None, None]
        z_new = n + be * z
        s_new = w + be * s
        p_new = u + be * p
        x_new = x + al * p_new
        r_new = r - al * s_new
        u_new = u - al * apply_dinv_batched(s_new, d)
        w_new = w - al * z_new

        pp_new = uu + 2.0 * beta * up + beta * beta * pp
        dw2 = alpha * alpha * pp_new
        diff = jnp.sqrt(dw2 * hw) if weighted else jnp.sqrt(dw2)
        converged = ~breakdown & (diff < delta)
        diff = jnp.where(breakdown, diff_prev, diff)

        # lane quarantine from the scalars already in hand (a poisoned
        # lane's bundle is non-finite) — batched_pcg's contract
        sick = active & ~(
            jnp.isfinite(gamma) & jnp.isfinite(denom) & jnp.isfinite(diff)
        )
        breakdown = breakdown & ~sick
        converged = converged & ~sick

        upd = (active & ~breakdown & ~sick)[:, None, None]
        keep = lambda old, new: jnp.where(upd, new, old)
        follow = active & ~breakdown & ~sick
        return (
            k + 1,
            keep(x, x_new), keep(r, r_new), keep(u, u_new), keep(w, w_new),
            keep(z, z_new), keep(s, s_new), keep(p, p_new),
            jnp.where(follow, gamma, g_prev),
            jnp.where(active & ~sick, diff, diff_prev),
            conv | (active & converged),
            bd | (active & breakdown),
            quar | sick,
            jnp.where(active, k + 1, iters),
        )

    return body


def result_of(state) -> BatchedPCGResult:
    """View a batched pipelined carry as a BatchedPCGResult."""
    return BatchedPCGResult(
        w=state[1], iters=state[13], diff=state[9],
        converged=state[10], breakdown=state[11], quarantined=state[12],
    )


def pcg_batched_pipelined(problem: Problem, a, b, rhs, mask=None,
                          stencil: str = "xla",
                          interpret=None) -> BatchedPCGResult:
    """Run batched pipelined PCG for pre-assembled operands (the
    ``batch.batched_pcg.pcg_batched`` contract, pipelined recurrence;
    ``stencil="pallas"`` takes the fused lane-on-grid kernel)."""
    state = advance(
        problem, a, b, rhs,
        init_state(problem, a, b, rhs, mask=mask, stencil=stencil,
                   interpret=interpret),
        mask=mask, stencil=stencil, interpret=interpret,
    )
    return result_of(state)
