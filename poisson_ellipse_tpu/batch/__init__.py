"""Batched multi-solve engines: one dispatch, B independent problems.

The throughput layer of the zoo (ISSUE 5): ``batched_pcg`` /
``batched_pipelined`` run B lanes — stacked RHS, per-lane ε/geometry
allowed — inside one fused ``lax.while_loop`` with per-lane masked
updates and in-loop NaN-lane quarantine; ``driver.solve_batched`` is the
chunked form that reports quarantines as ``recovery:lane-quarantine``
trace events and hosts fault injection; ``parallel.batched_sharded``
shards lanes over a mesh at one psum per iteration; and
``runtime.compile_cache`` serves arbitrary request sizes from bucketed
AOT executables of these engines.
"""

from poisson_ellipse_tpu.batch.batched_pcg import (
    BatchedPCGResult,
    batched_operands,
    pcg_batched,
)
from poisson_ellipse_tpu.batch.batched_pipelined import pcg_batched_pipelined
from poisson_ellipse_tpu.batch.driver import (
    BATCHED_ENGINES,
    GuardedBatchedResult,
    solve_batched,
)

__all__ = [
    "BATCHED_ENGINES",
    "BatchedPCGResult",
    "GuardedBatchedResult",
    "batched_operands",
    "pcg_batched",
    "pcg_batched_pipelined",
    "solve_batched",
]
