"""Batched classical PCG: B independent solves in ONE fused while_loop.

Every engine in the zoo runs exactly one Poisson solve per dispatch; at
small grids that leaves the chip dispatch/latency-bound (BENCH_r05:
1.29 ms at 400×600 — far below what the FLOPs/HBM sustain when lanes are
stacked). The paper's scheme is embarrassingly batchable: the PCG
recurrence is identical for every problem, only the fictitious-domain
operands (a, b, rhs — and through them ε and the geometry) differ. This
module stacks B such problems on a leading *lane* dimension,
``(B, M+1, N+1)``, and runs them through ONE ``lax.while_loop``:

- **Per-lane masked updates.** Each lane carries its own scalar
  recurrence (zr, diff, α, β as (B,) arrays) and its own exit flags.
  A lane that converges or breaks down is *frozen* — subsequent
  iterations recompute its updates but discard them via ``where`` — so
  the loop runs until every lane is done while each lane's trajectory
  is exactly the single-engine one. Lane arithmetic is never coupled:
  lane 0 of a batched solve is **bit-identical** to the corresponding
  single solve (asserted in ``tests/test_batched.py``).

- **Stacked reductions.** The per-iteration dot bundle is computed for
  all lanes in one pass — ``jnp.sum(u*v, axis=(1, 2))`` stacked into a
  single ``(k, B)`` reduction — the ``ops.reduction.grid_dots`` idiom
  widened by a lane axis. On the lane-sharded mesh this is what keeps
  the collective count flat in B (``parallel.batched_sharded``).

- **In-loop lane quarantine.** A NaN in one lane's carry surfaces in
  that lane's *scalars* (its dots sum the NaN), so the loop detects a
  poisoned lane from the (B,) reduction results it already has — zero
  extra array passes — and masks it out (``quarantined`` flag) instead
  of letting it spin to the iteration cap and poison the batch's wall
  clock. The chunked driver (``batch.driver``) surfaces each quarantine
  as a ``recovery:lane-quarantine`` trace event at the next chunk
  boundary, reusing the resilience chunk machinery.

- **Bucket embedding.** All shape-dependent scalars (h1, h2, δ, the
  iteration limit) are accepted as *traced* values, and an optional
  interior ``mask`` pins nodes outside an embedded true problem to
  zero — together these let ``runtime.compile_cache`` compile one
  executable per (bucketed) shape and serve any smaller request from it
  by pad-and-mask, with no retrace. With ``mask=None`` the traced
  computation is exactly the unmasked one (no extra ops).

Semantics per lane match ``solver.pcg`` clause for clause (breakdown
discards its update; a converged iteration keeps it; iteration counts
include the exiting body).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.precision import (
    load as _pload,
    resolve_storage_dtype,
    store as _pstore,
)
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD


class BatchedPCGResult(NamedTuple):
    """Per-lane solver output: everything ``PCGResult`` reports, plus the
    quarantine mask (lanes masked out after a non-finite carry)."""

    w: jax.Array           # (B, M+1, N+1) per-lane solutions
    iters: jax.Array       # (B,) iteration each lane finished at
    diff: jax.Array        # (B,) final step-norm per lane
    converged: jax.Array   # (B,) bool
    breakdown: jax.Array   # (B,) bool
    quarantined: jax.Array  # (B,) bool — non-finite lane, masked out


def _lane_ops(a, b, mask):
    """Normalise operands to broadcastable lane form.

    ``a``/``b`` may be (g1, g2) — shared geometry across lanes, the
    common serving case, which also saves their HBM passes — or
    (B, g1, g2) per-lane (mixed ε / mixed geometry). ``mask`` is an
    optional interior indicator for bucket-embedded problems: (g1, g2)
    shared, or (B, g1, g2) per-lane when lanes of one batch embed
    *different* true shapes (the serve scheduler's mixed-shape packing).
    """
    a3 = a if a.ndim == 3 else a[None]
    b3 = b if b.ndim == 3 else b[None]
    m3 = None if mask is None else (mask if mask.ndim == 3 else mask[None])
    return a3, b3, m3


def _grid_scale(h):
    """``h`` as a lane-broadcastable grid factor: a scalar stays scalar
    (the single-problem path, expression tree unchanged — the bitwise
    contract); a (B,) per-lane spacing gains the (B, 1, 1) lane axis so
    mixed-shape lanes each difference by their own h."""
    return h if jnp.ndim(h) == 0 else h[:, None, None]


def apply_a_batched(w, a3, b3, h1, h2):
    """A·w per lane: (B, g1, g2) iterate, (1|B, g1, g2) coefficients.

    The expression tree mirrors ``ops.stencil.apply_a_block`` term for
    term (each difference divided by h before combining), so each lane's
    result is bit-identical to the single-chip stencil's. ``h1``/``h2``
    may be scalars (shared spacing) or (B,) per-lane.
    """
    h1, h2 = _grid_scale(h1), _grid_scale(h2)
    wc = w[:, 1:-1, 1:-1]
    ax = -(
        a3[:, 2:, 1:-1] * (w[:, 2:, 1:-1] - wc) / h1
        - a3[:, 1:-1, 1:-1] * (wc - w[:, :-2, 1:-1]) / h1
    ) / h1
    ay = -(
        b3[:, 1:-1, 2:] * (w[:, 1:-1, 2:] - wc) / h2
        - b3[:, 1:-1, 1:-1] * (wc - w[:, 1:-1, :-2]) / h2
    ) / h2
    return jnp.pad(ax + ay, ((0, 0), (1, 1), (1, 1)))


def diag_d_batched(a3, b3, h1, h2, mask=None):
    """Per-lane diagonal of A, zero boundary ring; ``mask`` additionally
    zeroes it outside an embedded true interior (bucket padding), which
    makes ``apply_dinv`` pin those nodes to zero for free. ``h1``/``h2``
    scalar or (B,) per-lane, as :func:`apply_a_batched`."""
    h1, h2 = _grid_scale(h1), _grid_scale(h2)
    d = (a3[:, 2:, 1:-1] + a3[:, 1:-1, 1:-1]) / (h1 * h1) + (
        b3[:, 1:-1, 2:] + b3[:, 1:-1, 1:-1]
    ) / (h2 * h2)
    d = jnp.pad(d, ((0, 0), (1, 1), (1, 1)))
    if mask is not None:
        d = d * mask
    return d


def apply_dinv_batched(r, d):
    """z = r / D with the zero guard, per lane (broadcasts (1|B, ...))."""
    safe = jnp.where(d != 0.0, d, 1.0)
    return jnp.where(d != 0.0, r / safe, 0.0)


def lane_dots(*pairs):
    """All per-lane Σ uᵢ·vᵢ as one stacked (k, B) reduction — the
    ``grid_dots`` fusion idiom widened by the lane axis. Sums are raw;
    callers apply their h1·h2 weights, exactly as ``grid_dots``."""
    return jnp.stack([jnp.sum(u * v, axis=(1, 2)) for u, v in pairs])


def init_state(problem: Problem, a, b, rhs, mask=None, h1=None, h2=None,
               storage_dtype=None, x0=None):
    """The batched PCG carry at iteration 0.

    Layout: (k, w, r, p, zr, diff, converged, breakdown, quarantined,
    iters) — the single-engine carry with (B,) per-lane scalars/flags
    plus the quarantine mask and the per-lane completion counter.
    ``h1``/``h2`` may be traced overrides (the bucket-generic path);
    they default to the problem's. ``storage_dtype`` stores the lane
    fields (w, r, p) at that width (``ops.precision``) — the per-lane
    scalars stay at compute width. ``x0`` is an optional per-lane warm
    start (B, g1, g2): the carry starts from it with the TRUE residual
    rhs − A·x0 (the ``solver.pcg.init_state`` warm-start contract, per
    lane — a wrong guess costs iterations, never correctness), masked
    to the embedded interior so bucket padding stays exactly zero.
    ``x0=None`` leaves every expression of the cold path untouched.
    """
    dtype = rhs.dtype
    st = resolve_storage_dtype(storage_dtype, dtype)
    B = rhs.shape[0]
    h1 = jnp.asarray(problem.h1 if h1 is None else h1, dtype)
    h2 = jnp.asarray(problem.h2 if h2 is None else h2, dtype)
    a3, b3, m3 = _lane_ops(a, b, mask)
    d = diag_d_batched(a3, b3, h1, h2, m3)
    if x0 is None:
        r0 = rhs
        w0 = jnp.zeros_like(rhs, dtype=st or rhs.dtype)
    else:
        w0 = jnp.asarray(x0, dtype)
        if m3 is not None:
            w0 = w0 * m3
        r0 = rhs - apply_a_batched(w0, a3, b3, h1, h2)
        if m3 is not None:
            r0 = r0 * m3
        w0 = _pstore(w0, st) if st is not None else w0
    z0 = apply_dinv_batched(r0, d)
    zr0 = jnp.sum(z0 * r0, axis=(1, 2)) * h1 * h2
    return (
        jnp.asarray(0, jnp.int32),
        w0,
        _pstore(r0, st),
        _pstore(z0, st),  # p0 = z0
        zr0,
        jnp.full((B,), jnp.inf, dtype),
        jnp.zeros((B,), bool),
        jnp.zeros((B,), bool),
        jnp.zeros((B,), bool),
        jnp.zeros((B,), jnp.int32),
    )


def advance(problem: Problem, a, b, rhs, state, limit=None, mask=None,
            h1=None, h2=None, delta=None, stencil: str = "xla",
            interpret=None, storage_dtype=None):
    """Advance the batched carry until every lane is done or iteration
    ``limit``. Chunked runs (limit=k, k+K, …) are bit-identical to one
    straight run — the ``solver.pcg.advance`` contract, per lane.

    ``h1``/``h2``/``delta``/``limit`` may all be traced scalars and
    ``mask`` a traced array: the bucket-generic executable of
    ``runtime.compile_cache`` is this function compiled once per padded
    shape, with every size-dependent number fed at dispatch.
    ``h1``/``h2``/``delta`` may further be (B,) per-lane and ``mask``
    (B, g1, g2) per-lane — the serve scheduler's mixed-shape packing,
    where lanes of one bucket executable host different true problems.

    ``stencil="pallas"`` routes A·p through the batched Pallas kernel
    (lane dimension on the kernel grid, ``ops.pallas_kernels.
    apply_a_batched_pallas``); it requires lane-shared coefficients and
    the problem's own concrete grid spacings (the kernel bakes h as
    compile-time constants).
    """
    if stencil == "pallas" and (h1 is not None or h2 is not None):
        raise ValueError(
            "the batched Pallas stencil bakes h1/h2 in as compile-time "
            "constants; traced overrides need stencil='xla' (the "
            "bucket-generic path)"
        )
    dtype = rhs.dtype
    st = resolve_storage_dtype(storage_dtype, dtype)
    if st is not None and stencil != "xla":
        raise ValueError(
            "storage_dtype on the batched engines rides the XLA stencil "
            "(the convert fuses into the consumers); the batched Pallas "
            "kernel is full-width"
        )
    h1 = jnp.asarray(problem.h1 if h1 is None else h1, dtype)
    h2 = jnp.asarray(problem.h2 if h2 is None else h2, dtype)
    delta = jnp.asarray(problem.delta if delta is None else delta, dtype)
    max_iter = (
        problem.max_iterations
        if limit is None
        else jnp.minimum(
            jnp.asarray(limit, jnp.int32), problem.max_iterations
        )
    )
    weighted = problem.norm == "weighted"
    a3, b3, m3 = _lane_ops(a, b, mask)
    d = diag_d_batched(a3, b3, h1, h2, m3)
    body = make_lane_step(a3, b3, d, m3, h1, h2, delta, weighted,
                          stencil=stencil, interpret=interpret,
                          hs=(problem.h1, problem.h2), storage_dtype=st)

    def cond(state):
        k, conv, bd, quar = state[0], state[6], state[7], state[8]
        return (k < max_iter) & jnp.any(~conv & ~bd & ~quar)

    return lax.while_loop(cond, body, state)


def make_lane_step(a3, b3, d, m3, h1, h2, delta, weighted,
                   stencil: str = "xla", interpret=None, hs=None,
                   storage_dtype=None):
    """One batched-classical iteration as a carry→carry function.

    Factored out of :func:`advance` so the lane-sharded composition
    (``parallel.batched_sharded``) runs the *identical* per-lane
    arithmetic inside ``shard_map`` — the loop driver changes, the
    iteration does not. ``stencil="pallas"`` takes the batched Pallas
    kernel (``hs`` supplies the concrete (h1, h2) it bakes in; lane-
    shared coefficients only).
    """
    st = storage_dtype
    dtype = jnp.result_type(h1)
    if st is not None:
        # operands stream narrow too (the byte cut covers every pass)
        a3_s, b3_s, d_s = _pstore(a3, st), _pstore(b3, st), _pstore(d, st)
    else:
        a3_s, b3_s, d_s = a3, b3, d
    if stencil == "pallas":
        from poisson_ellipse_tpu.ops.pallas_kernels import (
            apply_a_batched_pallas,
        )

        if a3.shape[0] != 1 or b3.shape[0] != 1:
            raise ValueError(
                "the batched Pallas stencil streams lane-shared "
                "coefficients; per-lane (B, g1, g2) a/b need stencil='xla'"
            )
        apply_stencil = lambda p: apply_a_batched_pallas(
            p, a3[0], b3[0], hs[0], hs[1], interpret=interpret
        )
    elif stencil == "xla":
        apply_stencil = lambda p: apply_a_batched(
            p, _pload(a3_s, dtype, st), _pload(b3_s, dtype, st), h1, h2
        )
    else:
        raise ValueError(f"unknown stencil: {stencil!r}")

    def body(state):
        k, w_sv, r_sv, p_sv, zr, diff_prev, conv, bd, quar, iters = state
        # tile-local upcast (identity without a storage dtype)
        w = _pload(w_sv, dtype, st)
        r = _pload(r_sv, dtype, st)
        p = _pload(p_sv, dtype, st)
        active = ~conv & ~bd & ~quar
        ap = apply_stencil(p)
        if m3 is not None:
            # bucket embedding: nodes outside the true interior stay
            # exactly zero (×1.0 elsewhere — a bitwise identity)
            ap = ap * m3
        denom = jnp.sum(ap * p, axis=(1, 2)) * h1 * h2
        breakdown = denom < DENOM_GUARD
        alpha = zr / jnp.where(breakdown, 1.0, denom)

        al = alpha[:, None, None]
        w_new = w + al * p
        r_new = r - al * ap
        z = apply_dinv_batched(r_new, _pload(d_s, dtype, st))

        # realised update (w_new − w), one stacked (2, B) reduction —
        # the grid_dots bundle per lane (solver.pcg.advance's fusion)
        dw = w_new - w
        sums = lane_dots((z, r_new), (dw, dw))
        zr_new = sums[0] * h1 * h2
        dw2 = sums[1]
        diff = jnp.sqrt(dw2 * h1 * h2) if weighted else jnp.sqrt(dw2)
        converged = ~breakdown & (diff < delta)
        diff = jnp.where(breakdown, diff_prev, diff)

        # lane quarantine from the scalars the reduction already paid
        # for: a poisoned lane's dots are non-finite, so no extra array
        # pass is needed to detect it. The lane keeps its pre-fault
        # carry and drops out of `active`.
        sick = active & ~(
            jnp.isfinite(denom) & jnp.isfinite(zr_new) & jnp.isfinite(diff)
        )
        breakdown = breakdown & ~sick
        converged = converged & ~sick

        beta = zr_new / zr
        p_new = z + beta[:, None, None] * p

        # per-lane freeze masks: an inactive (or newly-sick) lane keeps
        # its carry; a breakdown lane discards its own update (the
        # reference exits before touching w/r); a converged lane keeps
        # the update but freezes p/zr (solver.pcg.advance's where tree)
        upd = (active & ~breakdown & ~sick)[:, None, None]
        follow = (active & ~breakdown & ~converged & ~sick)
        w_out = jnp.where(upd, _pstore(w_new, st), w_sv)
        r_out = jnp.where(upd, _pstore(r_new, st), r_sv)
        p_out = jnp.where(follow[:, None, None], _pstore(p_new, st), p_sv)
        zr_out = jnp.where(follow, zr_new, zr)
        diff_out = jnp.where(active & ~sick, diff, diff_prev)
        iters_out = jnp.where(active, k + 1, iters)
        return (
            k + 1, w_out, r_out, p_out, zr_out, diff_out,
            conv | (active & converged),
            bd | (active & breakdown),
            quar | sick,
            iters_out,
        )

    return body


def result_of(state) -> BatchedPCGResult:
    """View a batched carry as a BatchedPCGResult."""
    return BatchedPCGResult(
        w=state[1], iters=state[9], diff=state[5],
        converged=state[6], breakdown=state[7], quarantined=state[8],
    )


def pcg_batched(problem: Problem, a, b, rhs, mask=None,
                stencil: str = "xla", interpret=None,
                storage_dtype=None) -> BatchedPCGResult:
    """Run batched PCG for pre-assembled operands.

    ``rhs`` is (B, M+1, N+1); ``a``/``b`` are (M+1, N+1) shared or
    (B, M+1, N+1) per-lane. Jit-safe with ``problem`` static.
    ``stencil``: "xla" (default, any operands) or "pallas" (the batched
    lane-on-grid kernel; shared coefficients, f32/bf16 on hardware).
    """
    state = advance(
        problem, a, b, rhs,
        init_state(problem, a, b, rhs, mask=mask,
                   storage_dtype=storage_dtype),
        mask=mask, stencil=stencil, interpret=interpret,
        storage_dtype=storage_dtype,
    )
    return result_of(state)


def batched_operands(problem: Problem, lanes: int, dtype=jnp.float32,
                     eps_values=None, geometry=None, theta=None):
    """Assemble (a, b, rhs) for a ``lanes``-wide batch of this problem.

    With ``eps_values`` (length ``lanes``) each lane gets its own
    fictitious-domain ε — per-lane (B, g1, g2) coefficients; otherwise
    the geometry is shared and a/b stay (g1, g2) (the cheaper layout).
    The RHS is the problem's, tiled: the throughput protocol solves B
    identical systems, which is measurement-honest because lanes never
    share arithmetic (no CSE is possible across the lane axis of one
    array).
    """
    import numpy as np

    from poisson_ellipse_tpu.ops import assembly

    if eps_values is not None:
        if len(eps_values) != lanes:
            raise ValueError(
                f"eps_values has {len(eps_values)} entries for {lanes} lanes"
            )
        abrs = [
            assembly.assemble_numpy(
                Problem(
                    M=problem.M, N=problem.N, a1=problem.a1, b1=problem.b1,
                    a2=problem.a2, b2=problem.b2, f_val=problem.f_val,
                    delta=problem.delta, norm=problem.norm, eps=eps,
                    max_iter=problem.max_iter,
                ),
                geometry=geometry, theta=theta,
            )
            for eps in eps_values
        ]
        np_dtype = assembly.numpy_dtype(dtype)
        a = jnp.asarray(np.stack([x[0] for x in abrs]).astype(np_dtype))
        b = jnp.asarray(np.stack([x[1] for x in abrs]).astype(np_dtype))
        rhs = jnp.asarray(np.stack([x[2] for x in abrs]).astype(np_dtype))
        return a, b, rhs
    a, b, rhs = assembly.assemble(problem, dtype, geometry=geometry,
                                  theta=theta)
    return a, b, jnp.broadcast_to(rhs, (lanes,) + rhs.shape)
