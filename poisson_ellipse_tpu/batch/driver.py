"""Chunked batched solves: quarantine as an *observed* event.

The batched engines already mask a poisoned lane out in-loop
(``batch.batched_pcg``: the quarantine test rides the scalars the dot
bundle computes anyway). What the fused loop cannot do is *tell anyone*:
a serving stack needs the quarantine on the wire — which lane, at which
iteration — and fault injection needs an exact iteration to corrupt the
carry at. Both are chunk-boundary jobs, and the resilience guard
(``resilience.guard``) already built that machinery: run the production
``advance`` in chunks (bit-identical to a straight run — chunking only
moves the while_loop boundary), read a tiny health word between chunks,
record ``recovery:*`` trace events through the same ``_record`` helper.

This driver reuses exactly that: per chunk, ONE host read of the
per-lane flag vector; each newly-quarantined lane emits a
``recovery:lane-quarantine`` event (the guard's event schema, lane in
the detail); ``FaultPlan``s inject lane-addressed faults at exact
iterations (``resilience.faultinject.Fault(lane=...)``); ``timeout``
cancels gracefully at a chunk boundary. Healthy lanes are untouched by
any of it — their trajectory is the fused single-dispatch one.
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from poisson_ellipse_tpu.batch import batched_pcg, batched_pipelined
from poisson_ellipse_tpu.batch.batched_pcg import (
    BatchedPCGResult,
    batched_operands,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.resilience.faultinject import FaultPlan
from poisson_ellipse_tpu.resilience.guard import (
    DEFAULT_CHUNK,
    HEALTH_NONFINITE,
    RecoveryEvent,
    _check_deadline,
    _record,
)

# the lane-batched engine names: the registry (solver.engine) is the
# single source of truth; re-exported here for the batch package surface
from poisson_ellipse_tpu.solver.engine import BATCHED_ENGINES  # noqa: E402

# carry-layout tables per engine: field-name → index (the FaultPlan
# addressing contract shared with resilience.guard's adapters), plus the
# per-lane flag/counter slots the driver reads between chunks
_LAYOUT = {
    "batched": {
        "module": batched_pcg,
        "fields": {"w": 1, "r": 2, "p": 3, "zr": 4},
        "zr": 4, "conv": 6, "bd": 7, "quar": 8, "iters": 9,
    },
    "batched-pipelined": {
        "module": batched_pipelined,
        "fields": {
            "x": 1, "r": 2, "u": 3, "w": 4, "z": 5, "s": 6, "p": 7,
            "gamma": 8,
        },
        "zr": 8, "conv": 10, "bd": 11, "quar": 12, "iters": 13,
    },
}


@functools.lru_cache(maxsize=32)
def _chunk_advance(engine: str, problem: Problem, masked: bool):
    """One jitted chunk-advance per (engine, problem, mask-arity),
    operands and bound passed as traced arguments — repeated
    ``solve_batched`` calls for the same problem reuse the compiled
    advance instead of retracing per request (the per-request
    recompile hazard tpulint TPU010 fences)."""
    mod = _LAYOUT[engine]["module"]
    if masked:

        def fn(a, b, rhs, state, lim, mask):
            return mod.advance(
                problem, a, b, rhs, state, limit=lim, mask=mask
            )

    else:

        def fn(a, b, rhs, state, lim):
            return mod.advance(problem, a, b, rhs, state, limit=lim)

    # no donation: operands are re-fed every chunk, and the in carry is
    # the caller's pre-fault rollback reference
    return jax.jit(fn)  # tpulint: disable=TPU004


class GuardedBatchedResult(NamedTuple):
    """A chunked batched solve's outcome: per-lane results plus the
    quarantine story (empty ``recoveries`` = every lane ran healthy)."""

    result: BatchedPCGResult
    recoveries: tuple[RecoveryEvent, ...]
    engine: str


def solve_batched(
    problem: Problem,
    lanes: int,
    engine: str = "batched",
    dtype=jnp.float32,
    *,
    operands=None,
    mask=None,
    chunk: int = DEFAULT_CHUNK,
    faults: Optional[FaultPlan] = None,
    timeout: Optional[float] = None,
) -> GuardedBatchedResult:
    """One chunked batched solve with lane-quarantine reporting.

    ``operands`` is an optional pre-assembled (a, b, rhs) triple (rhs
    lane-stacked); by default the problem is assembled and its RHS tiled
    over ``lanes``. ``faults`` injects lane-addressed carry corruption
    at exact iterations (``Fault(kind="nan", at_iter=k, lane=j)``).
    """
    if engine not in _LAYOUT:
        raise ValueError(
            f"unknown batched engine {engine!r} (one of {BATCHED_ENGINES})"
        )
    if chunk < 1:
        raise ValueError("chunk must be >= 1")
    lay = _LAYOUT[engine]
    mod = lay["module"]
    a, b, rhs = (
        operands if operands is not None
        else batched_operands(problem, lanes, dtype)
    )
    if rhs.shape[0] != lanes:
        raise ValueError(
            f"rhs carries {rhs.shape[0]} lanes, expected {lanes}"
        )
    plan = faults if faults is not None else FaultPlan()
    for fault in plan.faults:
        if fault.lane is None:
            raise ValueError(
                "batched carries hold per-lane state: faults must be "
                "lane-addressed (Fault(..., lane=j)) so the corruption "
                "lands on one lane's slice"
            )
        if not 0 <= fault.lane < lanes:
            raise ValueError(
                f"fault lane {fault.lane} outside the {lanes}-lane batch"
            )
    events: list[RecoveryEvent] = []
    t0 = time.monotonic()

    # one compiled advance for every chunk AND every later call with the
    # same (engine, problem): operands/bound are traced arguments, the
    # jitted callable is lru-cached — no recompile per chunk or per
    # request (the resilience adapters' stance, made cross-call)
    masked = mask is not None
    chunk_fn = _chunk_advance(engine, problem, masked)
    if masked:
        advance = lambda st, lim: chunk_fn(a, b, rhs, st, lim, mask)
    else:
        advance = lambda st, lim: chunk_fn(a, b, rhs, st, lim)
    state = mod.init_state(problem, a, b, rhs, mask=mask)
    k = 0
    max_iter = problem.max_iterations
    quar_seen = np.zeros((lanes,), bool)

    while True:
        _check_deadline(timeout, t0, k)
        stop = plan.next_stop(k - 1)  # a fault AT k fires before this chunk
        limit = min(k + chunk, max_iter)
        if stop is not None and k < stop:
            limit = min(limit, stop)
        run_state = plan.apply(
            k, state, lay["fields"], lay["bd"], lay["zr"]
        ) if plan else state
        state = advance(run_state, limit)
        # ONE host read per chunk: the per-lane flag vector (the guard's
        # health-word stance, vectorised over lanes)
        k = int(state[0])
        conv = np.asarray(state[lay["conv"]])
        bd = np.asarray(state[lay["bd"]])
        quar = np.asarray(state[lay["quar"]])
        iters = np.asarray(state[lay["iters"]])
        for lane in np.flatnonzero(quar & ~quar_seen):
            _record(
                events, "lane-quarantine", int(iters[lane]),
                HEALTH_NONFINITE, engine, detail=f"lane {int(lane)}",
                lane=int(lane),
            )
        quar_seen = quar
        if k >= max_iter or bool(np.all(conv | bd | quar)):
            break

    return GuardedBatchedResult(
        result=mod.result_of(state),
        recoveries=tuple(events),
        engine=engine,
    )
