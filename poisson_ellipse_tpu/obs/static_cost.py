"""Static cost accounting: collectives, FLOPs and HBM bytes from the jaxpr.

The perf properties this framework advertises are *structural* — the
pipelined sharded iteration issues ONE stacked ``psum`` where the
classical loop issues two; the halo exchange is four ``ppermute``s; an
iteration's HBM traffic is so-many array passes. Structural claims rot
silently unless they are read back from the compiled artifact itself.
This module does that reading, with no hardware in the loop:

- :func:`loop_primitive_counts` walks a function's jaxpr and counts the
  named primitives inside every ``while_loop`` body — the
  per-iteration count, by construction (branch arms of a ``lax.cond``
  inside the body count too: a static budget is an upper bound, and the
  residual-replacement branches deliberately add no collectives).
- :func:`xla_cost` asks XLA's HLO cost analysis for estimated FLOPs and
  bytes accessed. XLA analyses a ``while`` body once (the trip count is
  dynamic), so the computation total ≈ prologue + one iteration — the
  honest per-iteration estimate, labelled as such.
- :func:`engine_report` builds any engine through its real product
  entry point (``solver.engine.build_solver`` /
  ``parallel.pcg_sharded.build_sharded_solver``) and emits one record:
  psum/ppermute per iteration, estimated FLOPs/bytes, and the roofline
  traffic *model*'s passes/bytes side by side — the measured-vs-modeled
  columns ``harness inspect`` prints and BENCH artifacts carry.

The "pipelined = 1 psum/iter vs classical = 2" regression check lives on
top of this module (``tests/test_obs.py``, ``tests/test_pipelined.py``,
``bench.py``'s artifact) — one metric, asserted everywhere it matters,
instead of test-local jaxpr walks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.models.problem import Problem

# the jaxpr walk lives in analysis.jaxpr_scan (the contract matrix and
# this report read the SAME traversal); re-exported here because every
# cadence pin historically imports them from obs.static_cost
from poisson_ellipse_tpu.analysis.jaxpr_scan import (  # noqa: F401
    COLLECTIVE_PRIMS,
    count_primitives,
    loop_collectives,
    loop_primitive_counts,
    while_body_primitive_counts,
)

# derived from the ENGINE_CAPS contract metadata — an engine declares a
# sharded collective cadence iff it has a sharded form
from poisson_ellipse_tpu.solver.engine import SHARDED_ENGINES  # noqa: F401

# iterations advanced per while-loop body: the s-step engines run s
# iterations per body (matrix-powers block), every other engine runs 1.
# Collective counts read from a while body divide by this to become
# per-ITERATION figures — the denominator every cadence claim uses.
def iters_per_loop_body(engine: str, sstep_s: int = 4) -> int:
    return sstep_s if engine in ("sstep", "sstep-pallas") else 1


# -- XLA cost analysis -------------------------------------------------------


def xla_cost(fn, args) -> dict | None:
    """{"flops", "bytes_accessed"} from XLA's HLO cost analysis, or None
    when the backend does not expose one. A ``while`` body is analysed
    once (dynamic trip count), so these totals read as prologue + one
    iteration — the per-iteration estimate, not a whole-solve total."""
    try:
        # single-shot construction is the point: this jit exists only to
        # be lowered for its cost analysis, never dispatched
        compiled = jax.jit(fn).lower(*args).compile()  # tpulint: disable=TPU006
        analysis = compiled.cost_analysis()
    except Exception:  # tpulint: disable=TPU009 — introspection must never break a run
        return None
    if analysis is None:
        return None
    if isinstance(analysis, (list, tuple)):  # older jax: one dict per device
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    flops = analysis.get("flops")
    bytes_accessed = analysis.get("bytes accessed")
    if flops is None and bytes_accessed is None:
        return None
    return {
        "flops": float(flops) if flops is not None else None,
        "bytes_accessed": (
            float(bytes_accessed) if bytes_accessed is not None else None
        ),
    }


# -- the per-engine report ---------------------------------------------------


def _build(problem: Problem, engine: str, dtype, mode: str, mesh_shape,
           storage_dtype=None, sstep_s: int = 4):
    """(fn, args) through the same entry points the product runs."""
    if mode == "single":
        from poisson_ellipse_tpu.solver.engine import build_solver

        solver, args, _ = build_solver(
            problem, engine, dtype, storage_dtype=storage_dtype,
            sstep_s=sstep_s,
        )
        return solver, args
    if mode == "sharded":
        from poisson_ellipse_tpu.harness.run import resolve_mesh
        from poisson_ellipse_tpu.parallel.pcg_sharded import build_sharded_solver

        if engine not in SHARDED_ENGINES:
            raise ValueError(
                f"engine {engine!r} is single-device only "
                f"(sharded engines: {', '.join(SHARDED_ENGINES)})"
            )
        mesh = resolve_mesh(mesh_shape)
        if engine == "sstep":
            from poisson_ellipse_tpu.parallel.sstep_sharded import (
                build_sstep_sharded_solver,
            )

            return build_sstep_sharded_solver(
                problem, mesh, dtype, s=sstep_s,
                storage_dtype=storage_dtype,
            )
        if storage_dtype is not None:
            raise ValueError(
                "sharded storage-dtype tracing covers the sstep engine; "
                "the classical/pipelined sharded forms run full width"
            )
        if engine in ("mg-pcg", "cheb-pcg"):
            from poisson_ellipse_tpu.parallel.mg_sharded import (
                build_mg_sharded_solver,
            )
            from poisson_ellipse_tpu.solver.engine import (
                PRECOND_KIND_BY_ENGINE,
            )

            return build_mg_sharded_solver(
                problem, mesh, dtype,
                kind=PRECOND_KIND_BY_ENGINE[engine],
            )
        if engine == "fmg":
            from poisson_ellipse_tpu.parallel.mg_sharded import (
                build_fmg_sharded_solver,
            )

            return build_fmg_sharded_solver(problem, mesh, dtype)
        solver, args = build_sharded_solver(
            problem, mesh, dtype, stencil_impl=engine
        )
        return solver, args
    raise ValueError(f"unknown mode: {mode!r} (single or sharded)")


def engine_report(
    problem: Problem,
    engine: str = "xla",
    dtype=jnp.float32,
    mode: str = "single",
    mesh_shape: tuple[int, int] | None = None,
    with_xla_cost: bool = True,
    storage_dtype=None,
    sstep_s: int = 4,
) -> dict:
    """One engine's static cost record.

    Keys: engine/mode/grid/dtype/mesh identification; per-iteration
    collective counts (``psum_per_iter``, ``ppermute_per_iter``, the
    full ``collectives_per_iter`` map); XLA-estimated
    ``flops_per_iter_est`` / ``hbm_bytes_per_iter_est`` (None when the
    backend exposes no cost analysis); and the roofline traffic model's
    ``modeled_passes_per_iter`` / ``modeled_hbm_bytes_per_iter`` for the
    measured-vs-modeled comparison.

    The s-step engines advance ``sstep_s`` iterations per loop body;
    their per-iteration counts divide the body counts by
    ``iters_per_body`` (reported, with the raw body counts kept in
    ``psum_per_body``/``ppermute_per_body`` — the jaxpr-pinned facts).
    ``storage_dtype`` reports the narrow-storage build: the modeled HBM
    bytes column shows the storage-width byte bill (the ~2× cut the
    bandwidth bench key measures end to end).
    """
    from poisson_ellipse_tpu.harness.roofline import (
        modeled_hbm_bytes_per_iter,
        passes_per_iter,
    )

    from poisson_ellipse_tpu.ops.precision import resolve_storage_dtype

    storage_dtype = resolve_storage_dtype(storage_dtype, dtype)
    fn, args = _build(problem, engine, dtype, mode, mesh_shape,
                      storage_dtype=storage_dtype, sstep_s=sstep_s)
    counts = loop_primitive_counts(fn, args)
    cost = xla_cost(fn, args) if with_xla_cost else None
    try:
        passes = passes_per_iter(problem, engine, dtype, sstep_s=sstep_s,
                                 storage_dtype=storage_dtype)
        modeled_bytes = modeled_hbm_bytes_per_iter(
            problem, engine, dtype, storage_dtype=storage_dtype,
            sstep_s=sstep_s,
        )
    except ValueError:  # an engine without a traffic model stays reportable
        passes, modeled_bytes = None, None
    # psum and its invariant-spelled twin are one collective on the wire
    psum = counts.get("psum", 0) + counts.get("psum_invariant", 0)
    per_body = iters_per_loop_body(engine, sstep_s)
    # Krylov-recycling footprint: engines whose contract row declares the
    # recycle cell (solver.engine.ENGINE_CAPS) report the modeled HBM
    # bytes of the default-capacity Lanczos ring. A MODEL only — the
    # ring is opt-in (pcg(recycle=cap)); the default build traced above
    # carries no ring, which is exactly why the psum/ppermute columns
    # are unchanged by it (the recycle contract cell's jaxpr-pinned fact)
    from poisson_ellipse_tpu.solver.engine import ENGINE_CAPS

    ring_bytes = None
    ring_cap = None
    if ENGINE_CAPS.get(engine, {}).get("contracts", {}).get("recycle"):
        from poisson_ellipse_tpu.solver.recycle import (
            RECYCLE_CAP,
            ring_model_bytes,
        )

        ring_cap = RECYCLE_CAP
        ring_bytes = ring_model_bytes(problem, cap=ring_cap, dtype=dtype)
    return {
        "engine": engine,
        "mode": mode,
        "grid": [problem.M, problem.N],
        "dtype": jnp.dtype(dtype).name,
        "storage_dtype": (
            jnp.dtype(storage_dtype).name if storage_dtype is not None
            else None
        ),
        "mesh": list(mesh_shape) if mesh_shape is not None else None,
        "iters_per_body": per_body,
        "psum_per_body": psum,
        "ppermute_per_body": counts.get("ppermute", 0),
        "psum_per_iter": psum / per_body if per_body > 1 else psum,
        "ppermute_per_iter": (
            counts.get("ppermute", 0) / per_body
            if per_body > 1 else counts.get("ppermute", 0)
        ),
        "collectives_per_iter": {k: v for k, v in counts.items() if v},
        "flops_per_iter_est": cost["flops"] if cost else None,
        "hbm_bytes_per_iter_est": cost["bytes_accessed"] if cost else None,
        "modeled_passes_per_iter": passes,
        "modeled_hbm_bytes_per_iter": modeled_bytes,
        "recycle_ring_cap": ring_cap,
        "recycle_ring_model_bytes": ring_bytes,
    }


def collectives_table(
    problem: Problem,
    engines: tuple[str, ...] = ("xla", "pipelined"),
    dtype=jnp.float32,
    mesh_shape: tuple[int, int] = (1, 2),
) -> dict:
    """The BENCH-artifact collectives block: per-engine psum/ppermute
    counts on one mesh, cheap enough to ride every bench run (jaxpr
    trace only — no compile, no execution)."""
    rows = {}
    for engine in engines:
        rep = engine_report(
            problem, engine, dtype, mode="sharded", mesh_shape=mesh_shape,
            with_xla_cost=False,
        )
        rows[engine] = {
            "psum_per_iter": rep["psum_per_iter"],
            "ppermute_per_iter": rep["ppermute_per_iter"],
        }
    return {
        "available": True,
        "grid": [problem.M, problem.N],
        "mesh": list(mesh_shape),
        "engines": rows,
    }


def render_report(rep: dict) -> str:
    """Human-readable form of one :func:`engine_report` record (the
    ``harness inspect`` output)."""
    where = (
        f"sharded {rep['mesh'][0]}x{rep['mesh'][1]}"
        if rep["mode"] == "sharded" and rep["mesh"]
        else rep["mode"]
    )
    storage = rep.get("storage_dtype")
    lines = [
        f"engine {rep['engine']} ({where}), grid "
        f"{rep['grid'][0]}x{rep['grid'][1]}, dtype {rep['dtype']}"
        + (f" (storage {storage})" if storage else "")
        + ":",
        f"  psum/iter      {rep['psum_per_iter']:g}",
        f"  ppermute/iter  {rep['ppermute_per_iter']:g}",
    ]
    if rep.get("iters_per_body", 1) > 1:
        lines.append(
            f"  per while-body ({rep['iters_per_body']} iters): "
            f"{rep['psum_per_body']} psum, {rep['ppermute_per_body']} "
            "ppermute (the jaxpr-pinned s-step cadence)"
        )
    extra = {
        k: v
        for k, v in rep["collectives_per_iter"].items()
        if k not in ("psum", "psum_invariant", "ppermute")
    }
    for name, n in sorted(extra.items()):
        lines.append(f"  {name}/iter {' ' * max(0, 12 - len(name))}{n}")
    flops = rep["flops_per_iter_est"]
    hbm = rep["hbm_bytes_per_iter_est"]
    lines.append(
        "  est FLOPs/iter (XLA)     "
        + (f"{flops:.3e}" if flops is not None else "n/a")
    )
    lines.append(
        "  est HBM bytes/iter (XLA) "
        + (f"{hbm:.3e}" if hbm is not None else "n/a")
    )
    passes = rep["modeled_passes_per_iter"]
    modeled = rep["modeled_hbm_bytes_per_iter"]
    if passes is not None:
        lines.append(
            f"  modeled HBM bytes/iter   {modeled:.3e} "
            f"({passes:g} array passes, harness.roofline)"
        )
        if hbm:
            lines.append(
                f"  measured-vs-modeled      {hbm / modeled:.2f}x "
                "(XLA estimate / roofline model)"
            )
    ring = rep.get("recycle_ring_model_bytes")
    if ring is not None:
        lines.append(
            f"  recycle ring (opt-in)    {ring:.3e} bytes modeled "
            f"(cap {rep['recycle_ring_cap']} full grids, solver.recycle; "
            "loop psum/ppermute counts above are unchanged by it)"
        )
    return "\n".join(lines)
