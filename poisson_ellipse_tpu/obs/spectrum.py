"""Spectral diagnostics from the CG trace: κ(M⁻¹A) without touching A.

CG is a Lanczos process in disguise: the α/β coefficients the solver
already records on device (``obs.convergence``) determine the Lanczos
tridiagonal T_m of the preconditioned operator M⁻¹A in the M-inner
product (Golub & Van Loan §10.2; the same three-term recurrence the
Ghysels–Vanroose pipelined engine reorders). Its eigenvalues — the Ritz
values — approximate the operator's spectrum, the extremal ones first,
so a converged solve's trace yields the condition number κ(M⁻¹A) for
free. That number is what the iteration-count wall (546 @ 400×600 →
5889 @ 8192², BENCH_r05) *is*: iterations scale as √κ, and any future
preconditioner (multigrid/Chebyshev — ROADMAP item 1) must prove it
moved κ, not just anecdotes. This module is the yardstick.

Everything here is host-side numpy over a handful of scalars per
iteration — no solve, no device work, O(m²) at worst for the m-step
eigendecomposition (milliseconds for the bench grids).

Three layers:

- :func:`lanczos_tridiagonal` — (diagonal, off-diagonal) of T_m from a
  :class:`~poisson_ellipse_tpu.obs.convergence.ConvergenceTrace`,
  skipping the exact-0 α entries a breakdown iteration records (its
  update is discarded; 1/α is undefined for it) and the zero-filled
  tail past ``iters``.
- :func:`ritz_values` / :func:`spectrum_report` — Ritz values, κ
  estimate (measured exact to the dense-eigendecomposition oracle on
  small grids — pinned within 10% in ``tests/test_spectrum.py``), the
  asymptotic CG rate (√κ−1)/(√κ+1), the worst-case κ-bound iteration
  count, and the *sharp* prediction: scalar CG replayed on the Ritz
  model problem (:func:`predicted_iterations`). CG's actual iteration
  count sits far below the κ bound (superlinear convergence — measured
  ~75% below at 400×600); the model-problem replay reproduces it
  because T_m carries the whole spectral measure, not just its edges,
  and it extrapolates to tolerances the solve never reached.
- :func:`detect_plateaus` / stagnation flags — spans where the
  step-norm stopped making progress, the trace-level symptom the
  resilience guard's per-chunk stagnation word detects in flight.
"""

from __future__ import annotations

import math

import numpy as np

__all__ = [
    "cg_coefficients",
    "detect_plateaus",
    "eigenvalue_bounds",
    "lanczos_tridiagonal",
    "predicted_iterations",
    "ritz_decomposition",
    "ritz_values",
    "spectrum_report",
]

# Ritz values are INTERIOR estimates of the spectrum (λ_min is
# overestimated, λ_max underestimated, both converging outward as the
# Lanczos process runs), so consumers that need a covering interval —
# the Chebyshev setup in ``mg.cheby`` — widen by these defaults. λ_min
# of an ill-conditioned operator converges slowest, hence the larger
# slack on that side; λ_max of the Jacobi-preconditioned 5-point
# operator is provably ≤ 2 (Gershgorin: row center 1, radius ≤ 1), so
# the high side needs only a trim.
LMIN_SLACK = 0.5
LMAX_SLACK = 1.05


def eigenvalue_bounds(
    trace, lo_slack: float = LMIN_SLACK, hi_slack: float = LMAX_SLACK,
) -> tuple[float, float] | None:
    """(λ_lo, λ_hi) covering the spectrum of M⁻¹A, from a CG trace.

    The single source the Chebyshev/multigrid setup consumes
    (``mg.cheby``) and ``harness diagnose`` reports — one Lanczos
    reconstruction, not two. The extremal Ritz values are widened by
    the slack factors (see above) into an interval the true spectrum
    sits inside for any usably long trace. Returns None when the trace
    yields no usable positive spectrum (the caller falls back to the
    Gershgorin interval).
    """
    vals = ritz_values(trace)
    if vals.size == 0:
        return None
    lmin, lmax = float(vals[0]), float(vals[-1])
    if not (math.isfinite(lmin) and math.isfinite(lmax)) or lmin <= 0:
        return None
    return lmin * lo_slack, lmax * hi_slack


def _valid_series(trace) -> dict:
    """{field: float64 array of the valid entries} from a trace or a
    ``trace.valid()``-shaped dict (host-side callers may hold either)."""
    v = trace if isinstance(trace, dict) else trace.valid()
    return {k: np.asarray(val, dtype=np.float64) for k, val in v.items()}


def cg_coefficients(trace) -> tuple[np.ndarray, np.ndarray]:
    """(α, β) aligned and cleaned for the Lanczos reconstruction.

    Two trace conventions feed this, both recorded by
    ``obs.convergence``:

    - the classical engines record (α_k, β_k) computed at iteration k;
    - the pipelined recurrence records β one step earlier by its
      documented reordering, so its series leads with an exact 0 (no
      direction update built iteration 1's p). That sentinel is the
      realignment signature: drop it and the remaining β_j pair with
      α_j exactly as the classical series does.

    The series is then truncated at the first entry that cannot be a
    genuine CG coefficient: α must be finite and > 0 (a breakdown
    iteration discards its update and records α = 0 — terminal by the
    loop contract), β finite and > 0 (β = zr_new/zr of positive inner
    products; a poisoned f32 trace fails here). Truncation, not
    skipping — the recurrence after a corrupt step is meaningless.
    Returns (α of the m usable steps, β with ≥ m−1 entries).
    """
    v = _valid_series(trace)
    alpha, beta = v["alpha"], v["beta"]
    if beta.size and beta[0] == 0.0:
        beta = beta[1:]  # the pipelined one-step shift
    bad_a = np.nonzero(~(np.isfinite(alpha) & (alpha > 0)))[0]
    bad_b = np.nonzero(~(np.isfinite(beta) & (beta > 0)))[0]
    m = alpha.size
    if bad_a.size:
        m = min(m, int(bad_a[0]))
    if bad_b.size:
        # beta[j] first couples steps j and j+1: alpha stays usable
        # through index bad_b[0]
        m = min(m, int(bad_b[0]) + 1)
    return alpha[:m], beta[: max(m - 1, 0)]


def lanczos_tridiagonal(trace) -> tuple[np.ndarray, np.ndarray]:
    """(diagonal d, off-diagonal e) of the Lanczos matrix T_m.

    The textbook change of basis from the CG two-term recurrences:

        d_1 = 1/α_1,   d_j = 1/α_j + β_{j-1}/α_{j-1}   (j ≥ 2)
        e_j = √β_j / α_j                                 (j ≤ m−1)

    T_m is similar to the projection of M⁻¹A onto the Krylov space, so
    its eigenvalues estimate the *preconditioned* spectrum — the one
    that governs the iteration count.
    """
    alpha, beta = cg_coefficients(trace)
    m = alpha.size
    if m == 0:
        return np.empty(0), np.empty(0)
    beta = beta[: m - 1]
    d = np.empty(m)
    d[0] = 1.0 / alpha[0]
    if m > 1:
        d[1:] = 1.0 / alpha[1:] + beta / alpha[: m - 1]
    e = np.sqrt(beta) / alpha[: m - 1]
    return d, e


def _eigh_tridiagonal(d: np.ndarray, e: np.ndarray, vectors: bool = False):
    """Eigen-decomposition of a symmetric tridiagonal, scipy-accelerated
    when available (O(m²)); dense numpy otherwise. Host-side math only —
    the module must work wherever numpy does."""
    try:
        from scipy.linalg import eigh_tridiagonal

        if vectors:
            return eigh_tridiagonal(d, e)
        return eigh_tridiagonal(d, e, eigvals_only=True), None
    except ImportError:
        t = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
        if vectors:
            return np.linalg.eigh(t)
        return np.linalg.eigvalsh(t), None


def ritz_values(trace) -> np.ndarray:
    """Ascending Ritz values of M⁻¹A from the trace (empty when the
    trace holds no usable iteration)."""
    vals, _ = ritz_decomposition(trace)
    return vals


def ritz_decomposition(
    trace, max_steps: int | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """(ascending Ritz values θ, T_m eigenvectors Y) — the vectors form.

    ``Y[:, i]`` expresses the i-th Ritz vector in the Lanczos basis of
    the Krylov space the solve walked, so any stored spanning set of
    that space (``solver.recycle``'s direction ring) turns it into an
    approximate Ritz vector of M⁻¹A: the deflation basis W = P·Y that
    Krylov recycling projects out of the *next* related solve. Returns
    ``(empty, empty)`` when the trace holds no usable step; columns are
    sorted with their values.

    ``max_steps`` truncates the reconstruction to the leading Lanczos
    steps — T_j is itself the Lanczos matrix of the j-step process, so
    a consumer holding only the first j basis vectors (a bounded
    direction ring) gets the decomposition matching what it stored
    rather than coefficients it cannot apply.
    """
    d, e = lanczos_tridiagonal(trace)
    if max_steps is not None and d.size > max_steps:
        d, e = d[:max_steps], e[: max(max_steps - 1, 0)]
    if d.size == 0:
        return np.empty(0), np.empty((0, 0))
    vals, vecs = _eigh_tridiagonal(d, e, vectors=True)
    order = np.argsort(vals)
    if vecs is None:  # unreachable with both scipy and numpy backends
        vecs = np.eye(d.size)
    return vals[order], vecs[:, order]


def extremal_indices(m: int, k: int) -> np.ndarray:
    """Indices of the ``k`` extremal entries of an ascending length-``m``
    spectrum: the low end first (the modes that dominate CG's iteration
    count), the top for the remainder (the cut-cell outliers the
    fictitious-domain blend creates). The one selection rule shared by
    the recycling harvest (``solver.recycle``) and the deflated
    predictor below — the prediction must model the same modes the
    deflation removes."""
    k = max(0, min(int(k), int(m)))
    lo = (k + 1) // 2
    hi = k - lo
    return np.concatenate(
        [np.arange(lo), np.arange(m - hi, m)]
    ).astype(np.intp)


def predicted_iterations(
    trace, delta: float, diff0: float | None = None,
    max_model_iters: int | None = None, deflated_k: int = 0,
) -> int | None:
    """Iterations until the step norm crosses ``delta``, predicted by
    replaying scalar CG on the Ritz model problem.

    T_m = V Θ Vᵀ defines a diagonal model system (eigenvalues Θ, initial
    residual weights V[0,:]²) on which CG produces the *same* scalar
    trajectory the real solve did for its first m steps — so the
    model's step-norm crossing of ``delta/diff0`` (``diff0`` defaults to
    the trace's first recorded step norm) is a sharp iteration
    prediction, unlike the worst-case κ bound (which ignores the
    interior of the spectrum and overpredicts ~75% here). Returns None
    when the model never reaches the target within ``max_model_iters``
    (default 4m) — e.g. a tolerance beyond what m Ritz values resolve.

    The base model assumes a ZERO initial guess — the prediction for a
    warm-started (recycled) solve would be dishonest. ``deflated_k``
    makes it honest for the deflated warm start ``solver.recycle``
    builds: the k extremal Ritz components (``extremal_indices`` — the
    same modes the harvest keeps) are removed from the model's initial
    residual, so the replay runs on the deflated interval and predicts
    the recycled solve, not the cold one.
    """
    v = _valid_series(trace)
    if diff0 is None:
        diff0 = float(v["diff"][0]) if v["diff"].size else None
    if not diff0 or diff0 <= 0 or delta <= 0:
        return None
    d, e = lanczos_tridiagonal(trace)
    m = d.size
    if m == 0:
        return None
    theta, vecs = _eigh_tridiagonal(d, e, vectors=True)
    weights = vecs[0, :] ** 2 if vecs is not None else np.full(m, 1.0 / m)
    if deflated_k > 0:
        if deflated_k >= m:
            return None  # the whole model deflated: nothing left to predict
        weights = weights.copy()
        weights[extremal_indices(m, deflated_k)] = 0.0
        if not np.any(weights > 0):
            return None
    # scalar CG on A = diag(θ) with r0 components √w — exact arithmetic
    # (f64), no arrays bigger than m
    r = np.sqrt(np.maximum(weights, 0.0))
    p = r.copy()
    zr = float(r @ r)
    target_ratio = delta / diff0
    first_step = None
    cap = max_model_iters if max_model_iters is not None else 4 * m
    for k in range(1, cap + 1):
        ap = theta * p
        denom = float(p @ ap)
        if denom <= 0 or zr <= 0:
            return None
        step_alpha = zr / denom
        r = r - step_alpha * ap
        step = abs(step_alpha) * math.sqrt(float(p @ p))
        if first_step is None:
            first_step = step
        if first_step > 0 and step < target_ratio * first_step:
            return k
        zr_new = float(r @ r)
        if zr_new <= 0:
            return None
        p = r + (zr_new / zr) * p
        zr = zr_new
    return None


def detect_plateaus(
    diff: np.ndarray, window: int | None = None, drop: float = 0.9
) -> list[tuple[int, int]]:
    """Spans (start, end) — end exclusive — where the step norm's
    RUNNING MINIMUM failed to shrink below ``drop`` × its value
    ``window`` iterations earlier.

    Two calibration facts from the published-grid traces drive the
    defaults. The raw series is the wrong thing to test: f32 step norms
    oscillate iteration to iteration, so the running best is what
    stalls when the system stalls. And healthy CG *locally* stalls the
    running best for real stretches (measured: 85 consecutive
    no-improvement iterations inside the perfectly healthy 989-count
    800×1200 run) — a fixed window cries wolf on big grids, so the
    default window scales with the trace: ``max(32, n // 4)``, where
    the same healthy runs' worst window ratio is ≤ 0.41 against the
    0.9 threshold. A flagged span therefore means a quarter of the run
    passed without 10% progress — the trace-level version of the
    resilience guard's per-chunk stagnation word.
    """
    diff = np.asarray(diff, dtype=np.float64)
    n = diff.size
    if window is None:
        window = max(32, n // 4)
    if n <= window:
        return []
    best = np.minimum.accumulate(diff)
    flat = best[window:] >= drop * best[:-window]
    spans: list[tuple[int, int]] = []
    start = None
    for i, is_flat in enumerate(flat):
        k = i + window
        if is_flat and start is None:
            start = k
        elif not is_flat and start is not None:
            spans.append((start, k))
            start = None
    if start is not None:
        spans.append((start, n))
    return spans


def spectrum_report(
    trace, delta: float, actual_iters: int | None = None,
    plateau_window: int | None = None, deflated_k: int = 0,
) -> dict:
    """One JSON-able spectral record for a solve's trace.

    Keys: ``available``; ``iters`` (recorded) / ``lanczos_m`` (usable
    steps); ``lambda_min`` / ``lambda_max`` / ``kappa`` of M⁻¹A;
    ``cg_rate`` = (√κ−1)/(√κ+1); ``iters_bound`` — the worst-case
    κ-bound count ln(δ/diff₀)/ln(1/rate) (an upper envelope, not a
    prediction); ``predicted_iters`` — the sharp Ritz-model replay;
    ``predicted_err`` vs ``actual_iters`` (defaults to the trace's
    iteration count); ``plateaus`` spans and the ``stagnated`` flag.

    ``deflated_k`` > 0 marks the trace as feeding a k-mode Krylov-
    recycled warm start (``solver.recycle``): ``predicted_iters`` is
    then the DEFLATED-interval replay and the record carries an extra
    ``predicted_iters_recycled`` alongside the cold prediction — a
    recycled solve judged against the zero-start prediction would read
    as a false regression (or a false win) in ``harness diagnose``.
    """
    v = _valid_series(trace)
    n = int(v["diff"].size)
    if actual_iters is None:
        actual_iters = n
    base = {"available": False, "iters": n, "lanczos_m": 0}
    if n == 0:
        return base
    d, e = lanczos_tridiagonal(trace)
    m = int(d.size)
    if m == 0:
        return base
    vals, _ = _eigh_tridiagonal(d, e)
    lmin, lmax = float(vals.min()), float(vals.max())
    if not (math.isfinite(lmin) and math.isfinite(lmax)) or lmin <= 0:
        return {**base, "lanczos_m": m}
    kappa = lmax / lmin  # unrounded: the dense-oracle tests pin digits
    sq = math.sqrt(kappa)
    rate = (sq - 1.0) / (sq + 1.0)
    diff0 = float(v["diff"][0])
    iters_bound = None
    if 0 < rate < 1 and diff0 > 0 and 0 < delta < diff0:
        iters_bound = int(math.ceil(math.log(delta / diff0) / math.log(rate)))
    cold = predicted_iterations(trace, delta, diff0=diff0)
    recycled = (
        predicted_iterations(trace, delta, diff0=diff0,
                             deflated_k=deflated_k)
        if deflated_k > 0 else None
    )
    predicted = recycled if deflated_k > 0 else cold
    plateaus = detect_plateaus(v["diff"], window=plateau_window)
    return {
        "available": True,
        "iters": n,
        "lanczos_m": m,
        "lambda_min": lmin,
        "lambda_max": lmax,
        "kappa": kappa,
        "cg_rate": rate,
        "iters_bound": iters_bound,
        "predicted_iters": predicted,
        "actual_iters": int(actual_iters),
        "predicted_err": (
            round(predicted / actual_iters - 1.0, 4)
            if predicted is not None and actual_iters
            else None
        ),
        **(
            {"deflated_k": int(deflated_k),
             "predicted_iters_cold": cold,
             "predicted_iters_recycled": recycled}
            if deflated_k > 0 else {}
        ),
        "plateaus": [[int(a), int(b)] for a, b in plateaus],
        "stagnated": bool(plateaus),
    }


def render_report(rep: dict) -> str:
    """Human-readable form of one :func:`spectrum_report` record (the
    spectral half of ``harness diagnose``)."""
    if not rep.get("available"):
        return (
            f"spectrum: unavailable ({rep.get('iters', 0)} iterations "
            "recorded, no usable Lanczos step)"
        )
    lines = [
        f"spectrum ({rep['lanczos_m']} Lanczos steps from "
        f"{rep['iters']} iterations):",
        f"  lambda(M^-1 A)        [{rep['lambda_min']:.6g}, "
        f"{rep['lambda_max']:.6g}]",
        f"  kappa                 {rep['kappa']:.6g}",
        f"  asymptotic CG rate    {rep['cg_rate']:.6f}  "
        "((sqrt(k)-1)/(sqrt(k)+1))",
    ]
    if rep.get("iters_bound") is not None:
        lines.append(
            f"  kappa-bound iters     {rep['iters_bound']}  (worst case)"
        )
    if rep.get("predicted_iters") is not None:
        err = rep.get("predicted_err")
        model = (
            f"deflated Ritz-model replay, k={rep['deflated_k']}"
            if rep.get("deflated_k") else "Ritz-model replay"
        )
        lines.append(
            f"  predicted iters       {rep['predicted_iters']}  "
            f"({model}; actual {rep['actual_iters']}"
            + (f", {err:+.1%}" if err is not None else "")
            + ")"
        )
    elif rep.get("deflated_k"):
        lines.append(
            "  predicted iters       n/a (warm start deflated past the "
            "model's resolution — cold prediction skipped as dishonest)"
        )
    if rep.get("plateaus"):
        spans = ", ".join(f"{a}..{b}" for a, b in rep["plateaus"])
        lines.append(f"  plateaus              {spans} (STAGNATION)")
    else:
        lines.append("  plateaus              none")
    return "\n".join(lines)
