"""On-device convergence telemetry: per-iteration history with zero syncs.

The reference can print per-iteration residuals because its scalar
recurrence lives on the host; this framework's loops are single fused
``lax.while_loop``s, so a convergence stall or an f32 breakdown is
invisible — only the final ``PCGResult`` scalars come back. The fix is
NOT a host callback per iteration (the stage4 anti-pattern, now linted
as tpulint TPU008): it is a preallocated on-device ring of scalar
buffers carried through the loop, scattered into by
``lax.dynamic_update_slice`` at index ``k`` inside the body. The whole
history rides the one device→host transfer the result already pays.

Four series are recorded per iteration, one (cap,) buffer each, in
:data:`HISTORY_FIELDS` order:

  zr     the iteration's preconditioned-residual inner product — the
         classical loop's ``zr_new = (z, r)``; the pipelined loop's γ
         (the same quantity, one recurrence step earlier by that
         engine's documented reordering); always the raw computed value,
         before any breakdown/convergence hold.
  diff   the step norm ‖Δw‖ as stored into the carry (on a breakdown
         iteration this is the held previous value, exactly what the
         solver itself reports).
  alpha  the step length the iteration applied — exactly 0 on a
         breakdown iteration (its update is discarded), identically in
         every engine's trace.
  beta   the raw direction update coefficient the iteration computed.

Contract, pinned by ``tests/test_obs.py``: recording never changes the
iterate trajectory (the history ops are pure additions — bit-identical
results with history on/off), and with history *disabled* the emitted
jaxpr is exactly today's (no ``dynamic_update_slice``, the original
carry arity — the feature costs zero when off).

Buffers are sized by the solve's iteration cap
(``Problem.max_iterations``, the reference's (M-1)(N-1)); four f32
buffers at the 800×1200 headline grid are ~15 MB total on a 16 GB part.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

HISTORY_FIELDS = ("zr", "diff", "alpha", "beta")


class ConvergenceTrace(NamedTuple):
    """Per-iteration solver history; entries ``[:iters]`` are valid.

    The buffers stay full-length (``cap``) and zero-filled past
    ``iters`` — trimming is a host-side choice (:meth:`valid`), never a
    device-side reshape.
    """

    iters: jax.Array
    zr: jax.Array
    diff: jax.Array
    alpha: jax.Array
    beta: jax.Array

    def valid(self) -> dict:
        """Host-side view: {field: np.ndarray of the iters valid entries}."""
        import numpy as np

        n = int(self.iters)
        return {
            name: np.asarray(getattr(self, name))[:n]
            for name in HISTORY_FIELDS
        }


def history_init(cap: int, dtype) -> tuple:
    """The zeroed history carry: one (cap,) buffer per field."""
    return tuple(jnp.zeros((int(cap),), dtype) for _ in HISTORY_FIELDS)


def history_record(hist: tuple, k, zr, diff, alpha, beta) -> tuple:
    """Scatter one iteration's scalars into the buffers at index ``k``.

    Pure on-device arithmetic (``dynamic_update_slice`` of a length-1
    slice) — no callback, no transfer, nothing the loop must wait on.
    """
    return tuple(
        lax.dynamic_update_slice(
            buf, jnp.reshape(val, (1,)).astype(buf.dtype), (k,)
        )
        for buf, val in zip(hist, (zr, diff, alpha, beta))
    )


def trace_of(hist: tuple, iters) -> ConvergenceTrace:
    """View a history carry as a ConvergenceTrace."""
    zr, diff, alpha, beta = hist
    return ConvergenceTrace(iters=iters, zr=zr, diff=diff, alpha=alpha, beta=beta)
