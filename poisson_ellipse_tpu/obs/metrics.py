"""Counters and gauges: the trace stream's aggregate half.

The reference's five stage4 accumulators (``T_gpu/T_copy/T_mpi/T_prec/
T_dot``, ``poisson_mpi_cuda2.cu:696-700``) are exactly this shape — named
scalars incremented around work and printed once at the end. Here the
registry is generic (any subsystem can mint a counter or gauge), and
:meth:`MetricsRegistry.emit` publishes the whole registry into the
ambient JSONL trace as ``counter``/``gauge`` records, so the aggregates
land in the same machine-readable stream as the spans they summarise.

Counters and gauges are *host-side* state: incrementing one from inside
a traced loop body would be a host sync per iteration (tpulint TPU008's
anti-pattern). On-device per-iteration series belong to
:mod:`.convergence`; this module is for per-run aggregates.
"""

from __future__ import annotations

import dataclasses
import threading

from poisson_ellipse_tpu.obs import trace as _trace


@dataclasses.dataclass
class Counter:
    """A monotonically increasing named value."""

    name: str
    value: float = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease (inc({n}))")
        self.value += n


@dataclasses.dataclass
class Gauge:
    """A named value that holds its most recent observation."""

    name: str
    value: float | None = None

    def set(self, v: float) -> None:
        self.value = float(v)


class MetricsRegistry:
    """Create-or-get registry of counters and gauges.

    A name is permanently one kind: asking for ``counter("x")`` after
    ``gauge("x")`` is a programming error and raises, instead of silently
    shadowing one metric with another.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name in self._gauges:
                raise ValueError(f"{name!r} is already a gauge")
            return self._counters.setdefault(name, Counter(name))

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name in self._counters:
                raise ValueError(f"{name!r} is already a counter")
            return self._gauges.setdefault(name, Gauge(name))

    def snapshot(self) -> dict:
        """{"counters": {name: value}, "gauges": {name: value}} — set
        gauges only (an unobserved gauge has nothing to report)."""
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {
                    n: g.value
                    for n, g in self._gauges.items()
                    if g.value is not None
                },
            }

    def emit(self, tracer=None) -> None:
        """Publish every metric into the JSONL trace (ambient tracer by
        default; silently nothing when tracing is inactive)."""
        tracer = tracer or _trace.active()
        if tracer is None:
            return
        snap = self.snapshot()
        for name, value in sorted(snap["counters"].items()):
            tracer.emit("counter", name, value=value)
        for name, value in sorted(snap["gauges"].items()):
            tracer.emit("gauge", name, value=value)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()


# the process-default registry (the harness/bench drivers use this one)
REGISTRY = MetricsRegistry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)
