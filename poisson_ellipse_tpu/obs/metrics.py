"""Counters, gauges and histograms: the trace stream's aggregate half.

The reference's five stage4 accumulators (``T_gpu/T_copy/T_mpi/T_prec/
T_dot``, ``poisson_mpi_cuda2.cu:696-700``) are exactly this shape — named
scalars incremented around work and printed once at the end. Here the
registry is generic (any subsystem can mint a counter or gauge), and
:meth:`MetricsRegistry.emit` publishes the whole registry into the
ambient JSONL trace as ``counter``/``gauge`` records, so the aggregates
land in the same machine-readable stream as the spans they summarise.

Counters and gauges are *host-side* state: incrementing one from inside
a traced loop body would be a host sync per iteration (tpulint TPU008's
anti-pattern). On-device per-iteration series belong to
:mod:`.convergence`; this module is for per-run aggregates.

:class:`Histogram` adds the latency-distribution kind (p50/p90/p99 over
a sliding window, lifetime count/sum); :mod:`.export` renders a
registry snapshot in the OpenMetrics text format for scrapers.
"""

from __future__ import annotations

import dataclasses
import threading

from poisson_ellipse_tpu.obs import trace as _trace


@dataclasses.dataclass
class Counter:
    """A monotonically increasing named value."""

    name: str
    value: float = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease (inc({n}))")
        self.value += n


@dataclasses.dataclass
class Gauge:
    """A named value that holds its most recent observation."""

    name: str
    value: float | None = None

    def set(self, v: float) -> None:
        self.value = float(v)


# sliding-window cap per histogram: quantiles are computed over the most
# recent observations only, so a long-lived serving process stays O(1)
HISTOGRAM_WINDOW = 4096

HISTOGRAM_QUANTILES = (0.5, 0.9, 0.99)


@dataclasses.dataclass
class Histogram:
    """Latency-style observations with p50/p90/p99 quantiles.

    ``count``/``sum`` are lifetime totals; quantiles are nearest-rank
    over a sliding window of the last :data:`HISTOGRAM_WINDOW`
    observations (a bounded buffer — good enough for run reports and
    the OpenMetrics summary rendering, not a streaming sketch).
    """

    name: str
    count: int = 0
    sum: float = 0.0
    _window: list = dataclasses.field(default_factory=list)

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        self._window.append(v)
        if len(self._window) > HISTOGRAM_WINDOW:
            del self._window[: len(self._window) - HISTOGRAM_WINDOW]

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile over the window (None when empty)."""
        if not self._window:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        ordered = sorted(self._window)
        idx = min(int(q * len(ordered)), len(ordered) - 1)
        return ordered[idx]

    @property
    def window_occupancy(self) -> int:
        """Observations currently in the sliding window.

        The staleness guard: quantiles never age out by *time*, so a
        stalled server keeps publishing the p99 of whenever it last did
        work — identical, on the quantile samples alone, to a healthy
        quiet one. Occupancy rides next to the quantiles (snapshot
        ``window`` key, ``<name>_window`` OpenMetrics sample, a
        ``<name>_window`` gauge in the trace) so a scraper can pair a
        frozen p99 with a non-advancing lifetime ``count`` and flag the
        stall instead of trusting the latency.
        """
        return len(self._window)

    def summary(self) -> dict:
        """{"count", "sum", "p50", "p90", "p99", "window"} — the
        snapshot entry (``window`` = sliding-window occupancy, the
        staleness guard next to the quantiles it qualifies)."""
        out = {"count": self.count, "sum": self.sum}
        for q in HISTOGRAM_QUANTILES:
            out[f"p{int(q * 100)}"] = self.quantile(q)
        out["window"] = self.window_occupancy
        return out


class MetricsRegistry:
    """Create-or-get registry of counters and gauges.

    A name is permanently one kind: asking for ``counter("x")`` after
    ``gauge("x")`` is a programming error and raises, instead of silently
    shadowing one metric with another.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def _check_kind(self, name: str, want: str) -> None:
        for kind, table in (
            ("counter", self._counters),
            ("gauge", self._gauges),
            ("histogram", self._histograms),
        ):
            if kind != want and name in table:
                raise ValueError(f"{name!r} is already a {kind}")

    def counter(self, name: str) -> Counter:
        with self._lock:
            self._check_kind(name, "counter")
            return self._counters.setdefault(name, Counter(name))

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            self._check_kind(name, "gauge")
            return self._gauges.setdefault(name, Gauge(name))

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            self._check_kind(name, "histogram")
            return self._histograms.setdefault(name, Histogram(name))

    def snapshot(self) -> dict:
        """{"counters": {...}, "gauges": {...}, "histograms": {...}} —
        set gauges only (an unobserved gauge has nothing to report).

        Deterministic: every table is name-sorted, not creation-ordered,
        so two snapshots of the same state serialize identically and
        snapshot-derived artifacts (OpenMetrics files, trace records)
        diff cleanly across runs.
        """
        with self._lock:
            return {
                "counters": {
                    n: self._counters[n].value
                    for n in sorted(self._counters)
                },
                "gauges": {
                    n: self._gauges[n].value
                    for n in sorted(self._gauges)
                    if self._gauges[n].value is not None
                },
                "histograms": {
                    n: self._histograms[n].summary()
                    for n in sorted(self._histograms)
                    if self._histograms[n].count
                },
            }

    def emit(self, tracer=None) -> None:
        """Publish every metric into the JSONL trace (ambient tracer by
        default; silently nothing when tracing is inactive or the tracer
        is already closed — a late emit after ``trace.stop()`` must not
        raise on a closed file, it has nowhere to publish)."""
        tracer = tracer or _trace.active()
        if tracer is None or getattr(tracer, "closed", False):
            return
        snap = self.snapshot()
        for name, value in snap["counters"].items():
            tracer.emit("counter", name, value=value)
        for name, value in snap["gauges"].items():
            tracer.emit("gauge", name, value=value)
        for name, summary in snap["histograms"].items():
            # the closed record-kind set has no histogram kind: quantiles
            # publish as gauges, the lifetime count as a counter
            tracer.emit("counter", f"{name}_count", value=summary["count"])
            tracer.emit("gauge", f"{name}_sum", value=summary["sum"])
            # the staleness guard: window occupancy as its own gauge, so
            # a frozen p99 is distinguishable from a healthy quiet one
            tracer.emit("gauge", f"{name}_window", value=summary["window"])
            for q in HISTOGRAM_QUANTILES:
                key = f"p{int(q * 100)}"
                if summary[key] is not None:
                    tracer.emit("gauge", f"{name}_{key}", value=summary[key])

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


# the process-default registry (the harness/bench drivers use this one)
REGISTRY = MetricsRegistry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return REGISTRY.histogram(name)


# -- the fleet vocabulary ----------------------------------------------------
#
# One spelling for the replicated-serving series (fleet.*), so dashboards,
# tests and the OpenMetrics snapshot agree on names:
#
#   fleet_queue_depth_r{i}       gauge      per-replica admission depth
#   fleet_in_flight_r{i}         gauge      per-replica laned requests
#   lease_expiry_total           counter    leases the router declared dead
#   fleet_handoff_total          counter    journal handoffs executed
#   fleet_handoff_requests_total counter    requests re-admitted by handoff
#   fleet_stale_writes_total     counter    fenced zombie writes rejected
#   handoff_latency_seconds      histogram  per-handoff journal→survivor time
#   fleet_rejoin_total           counter    dead replicas re-issued as fresh
#                                           incarnations (fleet survivability)
#   rejoin_latency_seconds       histogram  kill → first completed solve
#                                           delivered by the rejoined replica
#   fleet_starvation_total       counter    tenant-class starvation episodes
#                                           announced (serve.queue — loud,
#                                           never silent)

LEASE_EXPIRY_TOTAL = "lease_expiry_total"
FLEET_HANDOFF_TOTAL = "fleet_handoff_total"
FLEET_HANDOFF_REQUESTS_TOTAL = "fleet_handoff_requests_total"
FLEET_STALE_WRITES_TOTAL = "fleet_stale_writes_total"
HANDOFF_LATENCY_SECONDS = "handoff_latency_seconds"
FLEET_REJOIN_TOTAL = "fleet_rejoin_total"
REJOIN_LATENCY_SECONDS = "rejoin_latency_seconds"
FLEET_STARVATION_TOTAL = "fleet_starvation_total"


def replica_gauge(name: str, replica: int) -> Gauge:
    """The per-replica gauge ``<name>_r<replica>`` (flat names: the
    registry is label-free by design, so the replica index rides in the
    metric name exactly like the OpenMetrics snapshot renders it)."""
    return REGISTRY.gauge(f"{name}_r{replica}")
