"""Structured run tracing: dependency-free JSONL events with one schema.

The reference publishes its runs as free-form stdout tables (rank-0
printf blocks, ``poisson_mpi_cuda2.cu:1000-1034``); this framework's
drivers likewise grew ad-hoc ``print(..., file=sys.stderr)`` narration.
A serving stack needs the machine-readable form: every run emits a
stream of JSONL records — monotonic spans for the coarse phases
(assemble/compile/solve/finalize), point events for run reports and
bench rows, counters/gauges from :mod:`.metrics` — all under one run id
and one validated schema, so traces diff, grep and aggregate cleanly.

Activation is explicit (``--trace FILE`` on the harness CLI, or
:func:`start` from code) or ambient (the ``POISSON_TRACE`` environment
variable names the sink file); when no tracer is active every emitting
helper is a no-op, so instrumented code pays nothing. Nothing here
imports beyond the standard library — the tracer must work in the
leanest headless environment the solvers do.

Record schema (one JSON object per line; :func:`validate_record`):

  | key    | required | meaning                                        |
  |--------|----------|------------------------------------------------|
  | v      | yes      | schema version (``SCHEMA_VERSION``; v1 traces  |
  |        |          | still validate — v2 only added ``lane``)       |
  | run    | yes      | run id, shared by every record of one tracer   |
  | t      | yes      | seconds since the tracer started (monotonic)   |
  | kind   | yes      | meta / span / event / counter / gauge          |
  | name   | yes      | record name (``phase:solver``, ``bench_row``…) |
  | dur    | span     | span duration in seconds (monotonic)           |
  | value  | ctr/gauge| the counter/gauge value at emit time           |
  | lane   | no       | lane index of a lane-addressed event (the      |
  |        |          | batched engines' quarantine/fault records) —   |
  |        |          | first-class so lane filters need no field poke |
  | request| no       | request id of a request-addressed event (the   |
  | _id    |          | serve scheduler's admit/refill/retire/shed     |
  |        |          | records) — first-class so one request's whole  |
  |        |          | lifecycle greps out of a mixed stream          |
  | fields | no       | free-form JSON object of extra attributes      |

Timing inside traced device loops is out of scope by design: a span is a
*host-side* bracket, and the one rule (tpulint TPU008) is that no
emitting call ever lands inside a ``lax.while_loop`` body — on-device
per-iteration data goes through :mod:`.convergence` instead.
"""

from __future__ import annotations

import json
import os
import sys
import time
import uuid

# v2 added the optional top-level ``lane`` key (lane-addressed batched
# events); v3 the optional ``request_id`` key (request-addressed serving
# events); v1/v2 records remain valid — see VALID_VERSIONS
SCHEMA_VERSION = 3

VALID_VERSIONS = frozenset({1, 2, 3})

KINDS = frozenset({"meta", "span", "event", "counter", "gauge"})

# the closed top-level key set: unknown keys fail validation so the
# schema cannot grow silently (add here + bump SCHEMA_VERSION instead)
_ALLOWED_KEYS = frozenset(
    {
        "v", "run", "t", "kind", "name", "dur", "value", "lane",
        "request_id", "fields",
    }
)

ENV_VAR = "POISSON_TRACE"


class Tracer:
    """One run's JSONL event stream.

    ``sink`` is a path (opened for append, so multiple runs can share a
    file — each under its own run id) or any object with ``write``.
    Every record is flushed as it is written: a killed run keeps every
    event emitted before the kill, which is the point of tracing it.
    """

    def __init__(self, sink, run_id: str | None = None):
        if hasattr(sink, "write"):
            self._fh = sink
            self._owns = False
        else:
            self._fh = open(os.fspath(sink), "a", encoding="utf-8")
            self._owns = True
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self._t0 = time.monotonic()
        self.emit(
            "meta",
            "trace-start",
            fields={
                "schema": SCHEMA_VERSION,
                "pid": os.getpid(),
                "argv": list(sys.argv),
                "unix_time": time.time(),
            },
        )

    # -- emission -----------------------------------------------------------

    def emit(self, kind: str, name: str, dur: float | None = None,
             value: float | None = None, fields: dict | None = None,
             t: float | None = None, lane: int | None = None,
             request_id: str | None = None) -> None:
        if kind not in KINDS:
            raise ValueError(f"unknown record kind: {kind!r} (one of {sorted(KINDS)})")
        rec: dict = {
            "v": SCHEMA_VERSION,
            "run": self.run_id,
            "t": round(
                (time.monotonic() - self._t0) if t is None else max(t, 0.0), 6
            ),
            "kind": kind,
            "name": name,
        }
        if dur is not None:
            rec["dur"] = round(dur, 6)
        if value is not None:
            rec["value"] = value
        if lane is not None:
            rec["lane"] = int(lane)
        if request_id is not None:
            rec["request_id"] = str(request_id)
        if fields:
            rec["fields"] = fields
        # default=str: a numpy scalar or Path in a field must degrade to
        # text, never kill the traced run
        self._fh.write(json.dumps(rec, default=str) + "\n")
        self._fh.flush()

    def event(self, name: str, lane: int | None = None,
              request_id: str | None = None, **fields) -> None:
        self.emit(
            "event", name, fields=fields or None, lane=lane,
            request_id=request_id,
        )

    def span(self, name: str, **fields) -> "_Span":
        return _Span(self, name, fields)

    @property
    def closed(self) -> bool:
        """True once the sink can no longer accept records (late
        emitters — metrics flushes after ``stop()`` — check this
        instead of writing into a closed file)."""
        return bool(getattr(self._fh, "closed", False))

    def close(self) -> None:
        if self._owns and not self._fh.closed:
            self._fh.close()


class _Span:
    """Context manager emitting one ``span`` record at exit (monotonic
    duration; ``t`` is the span's start offset, as the schema table says)."""

    def __init__(self, tracer: Tracer, name: str, fields: dict):
        self.tracer = tracer
        self.name = name
        self.fields = fields

    def __enter__(self):
        self._start = time.monotonic()
        return self

    def __exit__(self, exc_type, *exc):
        fields = dict(self.fields)
        if exc_type is not None:
            fields["error"] = exc_type.__name__
        self.tracer.emit(
            "span",
            self.name,
            dur=time.monotonic() - self._start,
            fields=fields or None,
            # t is the span's START offset (the schema table's contract),
            # so spans sort and nest by when they began, not ended
            t=self._start - self.tracer._t0,
        )
        return False


# -- the ambient tracer ------------------------------------------------------

_active: Tracer | None = None
_env_checked = False


def start(sink, run_id: str | None = None) -> Tracer:
    """Open a tracer on ``sink`` and make it the ambient one."""
    global _active, _env_checked
    if _active is not None:
        _active.close()
    _active = Tracer(sink, run_id=run_id)
    _env_checked = True  # an explicit start outranks the env variable
    return _active


def stop() -> None:
    """Close and clear the ambient tracer (no-op when none is active).

    Re-arms the ``POISSON_TRACE`` lookup: an explicit start/stop cycle
    (e.g. the harness CLI's ``--trace``) must not permanently silence an
    env-requested trace for the rest of the process."""
    global _active, _env_checked
    if _active is not None:
        _active.close()
        _active = None
    _env_checked = False


def active() -> Tracer | None:
    """The ambient tracer; on first call, ``POISSON_TRACE=FILE`` in the
    environment starts one transparently."""
    global _active, _env_checked
    if _active is None and not _env_checked:
        _env_checked = True
        path = os.environ.get(ENV_VAR)
        if path:
            _active = Tracer(path)
    return _active


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def span(name: str, **fields):
    """A span on the ambient tracer, or a no-op context when inactive."""
    tracer = active()
    return tracer.span(name, **fields) if tracer else _NULL_SPAN


def span_event(name: str, dur: float, **fields) -> None:
    """Emit an already-measured span (the PhaseTimer shim's entry).

    The span ended "now", so its schema-mandated start offset is now
    minus ``dur`` — same t convention as a live ``span()`` bracket."""
    tracer = active()
    if tracer:
        tracer.emit(
            "span",
            name,
            dur=dur,
            fields=fields or None,
            t=(time.monotonic() - tracer._t0) - dur,
        )


def event(name: str, lane: int | None = None,
          request_id: str | None = None, **fields) -> None:
    tracer = active()
    if tracer:
        tracer.event(name, lane=lane, request_id=request_id, **fields)


def note(message: str, file=None, _event: str = "note", **fields) -> None:
    """Print ``message`` (stderr by default) AND emit it as a structured
    event when tracing — the drop-in for the drivers' ad-hoc narration
    prints, so human output and the machine trace cannot drift apart."""
    print(message, file=sys.stderr if file is None else file)
    tracer = active()
    if tracer:
        tracer.event(_event, message=message, **fields)


# -- schema validation -------------------------------------------------------


def validate_record(rec) -> str | None:
    """None when ``rec`` is a schema-valid trace record, else the error.

    The checks mirror the schema table in the module docstring; the
    dryrun smoke-check and the tests run every emitted line through this.
    """
    if not isinstance(rec, dict):
        return f"record is {type(rec).__name__}, not an object"
    unknown = set(rec) - _ALLOWED_KEYS
    if unknown:
        return f"unknown key(s): {', '.join(sorted(unknown))}"
    for key in ("v", "run", "t", "kind", "name"):
        if key not in rec:
            return f"missing required key: {key}"
    if rec["v"] not in VALID_VERSIONS:
        return (
            f"schema version {rec['v']!r} not one of "
            f"{sorted(VALID_VERSIONS)}"
        )
    if not isinstance(rec["run"], str) or not rec["run"]:
        return "run must be a non-empty string"
    if not isinstance(rec["t"], (int, float)) or rec["t"] < 0:
        return "t must be a non-negative number"
    if rec["kind"] not in KINDS:
        return f"kind {rec['kind']!r} not one of {sorted(KINDS)}"
    if not isinstance(rec["name"], str) or not rec["name"]:
        return "name must be a non-empty string"
    if rec["kind"] == "span":
        if not isinstance(rec.get("dur"), (int, float)) or rec["dur"] < 0:
            return "span records need a non-negative dur"
    if rec["kind"] in ("counter", "gauge"):
        if not isinstance(rec.get("value"), (int, float)):
            return f"{rec['kind']} records need a numeric value"
    if "lane" in rec:
        lane = rec["lane"]
        if isinstance(lane, bool) or not isinstance(lane, int) or lane < 0:
            return "lane must be a non-negative integer"
    if "request_id" in rec:
        rid = rec["request_id"]
        if not isinstance(rid, str) or not rid:
            return "request_id must be a non-empty string"
    if "fields" in rec and not isinstance(rec["fields"], dict):
        return "fields must be an object"
    return None


def read_jsonl(path) -> list[dict]:
    """Parse a trace file into records (blank lines skipped)."""
    out = []
    with open(os.fspath(path), encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: not JSON: {e}") from e
    return out


def validate_file(path) -> list[str]:
    """All schema errors in a trace file (empty list = valid)."""
    errors = []
    for i, rec in enumerate(read_jsonl(path), start=1):
        err = validate_record(rec)
        if err:
            errors.append(f"record {i}: {err}")
    return errors
