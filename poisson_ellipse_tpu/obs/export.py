"""Metrics export: OpenMetrics text format + snapshot-to-file wiring.

``obs.metrics`` aggregates; this module publishes. The wire format is
the OpenMetrics/Prometheus text exposition format — the lingua franca
every scraping stack already speaks — rendered from a
``MetricsRegistry.snapshot()``:

- counters  →  ``# TYPE <name> counter`` + ``<name>_total <v>``
- gauges    →  ``# TYPE <name> gauge``   + ``<name> <v>``
- histograms → ``# TYPE <name> summary`` + per-quantile samples
  (``<name>{quantile="0.5"} <v>`` …) + ``<name>_count`` / ``<name>_sum``

plus the ``# EOF`` terminator OpenMetrics mandates. Like the tracer,
nothing here imports beyond the standard library.

:func:`parse_openmetrics` / :func:`validate_openmetrics` are the
read-side: the renderer's output round-trips back into a snapshot-shaped
dict, and the validator is what the tests (and the dryrun gate) hold the
renderer to — an exporter whose output its own validator rejects is how
scrape endpoints rot silently.

:class:`MetricsExporter` is the file wiring: atomic (temp-then-rename)
one-shot ``write()``, and optional periodic snapshots on a daemon thread
(``interval_s``) — the hook a serving worker points its node scraper at,
inherited for free by anything that uses the default registry (the
harness CLI's ``--metrics FILE`` does exactly this).
"""

from __future__ import annotations

import os
import re
import tempfile
import threading

from poisson_ellipse_tpu.obs import metrics as _metrics

# OpenMetrics metric-name grammar; everything else maps onto "_"
_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")

_QUANTILE_BY_KEY = {
    f"p{int(q * 100)}": q for q in _metrics.HISTOGRAM_QUANTILES
}


def metric_name(name: str, prefix: str = "") -> str:
    """``name`` mapped onto the OpenMetrics grammar (prefixed, invalid
    characters → ``_``, leading digit guarded)."""
    full = f"{prefix}_{name}" if prefix else name
    full = _SANITIZE_RE.sub("_", full)
    if not full or not _NAME_RE.match(full):
        full = f"_{full}"
    return full


def render_openmetrics(snapshot: dict, prefix: str = "poisson") -> str:
    """One snapshot as OpenMetrics text (see module docstring).

    Deterministic: the snapshot is already name-sorted
    (``MetricsRegistry.snapshot``), and rendering adds no ordering of
    its own — two identical registries render byte-identically.
    """
    lines: list[str] = []
    for name, value in snapshot.get("counters", {}).items():
        full = metric_name(name, prefix)
        lines.append(f"# TYPE {full} counter")
        lines.append(f"{full}_total {_num(value)}")
    for name, value in snapshot.get("gauges", {}).items():
        full = metric_name(name, prefix)
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full} {_num(value)}")
    for name, summary in snapshot.get("histograms", {}).items():
        full = metric_name(name, prefix)
        lines.append(f"# TYPE {full} summary")
        for key, q in _QUANTILE_BY_KEY.items():
            if summary.get(key) is not None:
                lines.append(
                    f'{full}{{quantile="{q:g}"}} {_num(summary[key])}'
                )
        lines.append(f"{full}_count {_num(summary.get('count', 0))}")
        lines.append(f"{full}_sum {_num(summary.get('sum', 0.0))}")
        if summary.get("window") is not None:
            # sliding-window occupancy: the staleness guard a scraper
            # reads next to the quantiles (a stalled server's frozen p99
            # shows a window that stops turning over with count)
            lines.append(f"{full}_window {_num(summary['window'])}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def _num(v) -> str:
    """OpenMetrics sample value: repr(float) round-trips exactly, ints
    stay integral for readability."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def parse_openmetrics(text: str) -> dict:
    """Parse renderer-shaped OpenMetrics text back into a snapshot dict.

    Raises ``ValueError`` on anything malformed — use
    :func:`validate_openmetrics` for the error-list form.
    """
    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    histograms: dict[str, dict] = {}
    types: dict[str, str] = {}
    saw_eof = False
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line:
            continue
        if saw_eof:
            raise ValueError(f"line {lineno}: content after # EOF")
        if line == "# EOF":
            saw_eof = True
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                "counter", "gauge", "summary"
            ):
                raise ValueError(f"line {lineno}: malformed TYPE line")
            if not _NAME_RE.match(parts[2]):
                raise ValueError(
                    f"line {lineno}: invalid metric name {parts[2]!r}"
                )
            if parts[2] in types:
                raise ValueError(
                    f"line {lineno}: duplicate TYPE for {parts[2]!r}"
                )
            types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue  # HELP/comment lines: legal, carried by other tools
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$", line)
        if not m:
            raise ValueError(f"line {lineno}: not a sample line: {raw!r}")
        name, labels, value = m.group(1), m.group(2), m.group(3)
        try:
            value = float(value)
        except ValueError as e:
            raise ValueError(f"line {lineno}: non-numeric value") from e
        base, kind = _family_of(name, types)
        if kind is None:
            raise ValueError(
                f"line {lineno}: sample {name!r} precedes its TYPE line"
            )
        if kind == "counter":
            counters[base] = value
        elif kind == "gauge":
            gauges[base] = value
        else:
            entry = histograms.setdefault(base, {})
            if labels:
                qm = re.match(r'\{quantile="([0-9.eE+-]+)"\}$', labels)
                if not qm:
                    raise ValueError(
                        f"line {lineno}: summary sample needs a quantile label"
                    )
                entry[f"p{int(float(qm.group(1)) * 100)}"] = value
            elif name.endswith("_count"):
                entry["count"] = value
            elif name.endswith("_sum"):
                entry["sum"] = value
            elif name.endswith("_window"):
                entry["window"] = value
            else:
                raise ValueError(
                    f"line {lineno}: unlabelled summary sample {name!r}"
                )
    if not saw_eof:
        raise ValueError("missing # EOF terminator")
    return {"counters": counters, "gauges": gauges, "histograms": histograms}


def _family_of(sample_name: str, types: dict[str, str]):
    """(family base name, declared type) for one sample name."""
    if sample_name in types:
        return sample_name, types[sample_name]
    for suffix in ("_total", "_count", "_sum", "_window"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if base in types:
                return base, types[base]
    return sample_name, None


def validate_openmetrics(text: str) -> list[str]:
    """All format errors in an exposition (empty list = valid)."""
    try:
        parse_openmetrics(text)
        return []
    except ValueError as e:
        return [str(e)]


class MetricsExporter:
    """Snapshot-to-file wiring over a registry (default: the process
    registry). ``write()`` renders one atomic snapshot file;
    ``start()``/``stop()`` run it periodically on a daemon thread.
    Usable as a context manager (periodic while inside, final snapshot
    on exit)."""

    def __init__(self, path, registry=None, prefix: str = "poisson",
                 interval_s: float | None = None):
        self.path = os.fspath(path)
        self.registry = registry if registry is not None else _metrics.REGISTRY
        self.prefix = prefix
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def write(self) -> str:
        """Render the current snapshot to ``path`` (temp-then-rename, so
        a scraper never reads a torn file); returns the path."""
        text = render_openmetrics(self.registry.snapshot(), self.prefix)
        directory = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(
            prefix=".metrics-", suffix=".prom", dir=directory
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                fh.write(text)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return self.path

    def try_write(self) -> str | None:
        """``write()`` that reports instead of raising: returns None on
        success, the OSError text on failure. The one helper behind both
        halves of every ``--metrics`` consumer's contract — the
        fail-fast path probe at startup (error string → curated exit 2)
        and the never-crash final snapshot at exit (error string → a
        warning that must not discard the run's computed rc)."""
        try:
            self.write()
            return None
        except OSError as e:
            return str(e)

    def start(self) -> None:
        """Begin periodic snapshots (requires a positive ``interval_s``
        — ``Event.wait(0)`` returns immediately, so a non-positive
        cadence would busy-spin the daemon thread on atomic rewrites)."""
        if self.interval_s is None or self.interval_s <= 0:
            raise ValueError("periodic export needs a positive interval_s")
        if self._thread is not None:
            return
        self._stop.clear()

        def run():
            warned = False
            while not self._stop.wait(self.interval_s):
                try:
                    self.write()
                    warned = False
                except OSError as e:
                    # one transient failure (disk full, NFS blip) must
                    # not kill periodic export for the rest of the run;
                    # warn once per outage, keep trying
                    if not warned:
                        warned = True
                        import sys

                        print(
                            f"warning: periodic metrics snapshot failed "
                            f"({e}); retrying each interval",
                            file=sys.stderr,
                        )

        self._thread = threading.Thread(
            target=run, name="metrics-exporter", daemon=True
        )
        self._thread.start()

    def stop(self, final_write: bool = True) -> None:
        """Stop the periodic thread; by default flush one last snapshot
        (the at-exit state is the one a post-mortem wants)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_write:
            self.write()

    def __enter__(self) -> "MetricsExporter":
        if self.interval_s is not None and self.interval_s > 0:
            self.start()
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
