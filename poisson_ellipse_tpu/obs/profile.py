"""Fenced wall-clock phase profiling: where a solve's seconds actually go.

``harness.profile`` answers "what does one *iteration* spend per op"
(segmented on-device replay); this module answers the serving question
one level up: for one engine on one grid, how long are **compile**,
**H2D**, **solve** and **D2H** — the four phases a cold worker pays —
and what bandwidth/FLOP rate did the solve phase actually achieve,
joined against the static traffic model (``obs.static_cost`` /
``harness.roofline``) into a measured-vs-modeled roofline table with a
%-of-model column. Every phase is bracketed by real fences
(``utils.timing.fence`` = ``block_until_ready`` + a scalar fetch):
unfenced timing of async dispatches measures the queue, not the work —
the hazard tpulint TPU011 now flags structurally.

Phase map (one row per engine):

  t_build_s    host assembly + solver construction (f64 assembly,
               rounded once — the operand-fidelity contract)
  t_compile_s  ``jit(...).lower().compile()`` — the cold-start cost the
               AOT warm pool (``runtime.compile_cache``) exists to hide
  t_h2d_s      device_put of the host operands, fenced
  t_solve_s    median of ``repeat`` fenced dispatches of the compiled
               executable (plain-dispatch protocol: this is a phase
               *split*, not the bench's marginal-cost headline)
  t_d2h_s      materialising the solution grid on host

Rates, from the solve phase:

  hbm_gbps         modeled bytes/iter × iters / t_solve — achieved
                   streaming bandwidth under the traffic model (the
                   number the 82%-of-peak claim is made of)
  hbm_gbps_xla     XLA cost-analysis bytes/iter × iters / t_solve —
                   the compiler's own accounting of the same run
  flops_per_s      XLA cost-analysis FLOPs/iter × iters / t_solve
  pct_of_model     XLA bytes estimate / modeled bytes × 100 — the
                   %-of-model column; drift here means the traffic
                   model rotted against the compiled artifact
"""

from __future__ import annotations

import statistics
import time

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.utils.timing import fence

PROFILE_PHASES = ("build", "compile", "h2d", "solve", "d2h")


def profile_engine(
    problem: Problem,
    engine: str = "auto",
    dtype=jnp.float32,
    repeat: int = 3,
    with_xla_cost: bool = True,
) -> dict:
    """One engine's fenced phase/rate record (see module docstring).

    Single-lane engines only — the batched engines report throughput,
    not the single-solve phase split (``harness --lanes``).
    """
    import numpy as np

    from poisson_ellipse_tpu.harness.roofline import (
        hbm_peak_bytes_per_s,
        modeled_hbm_bytes_per_iter,
        passes_per_iter,
    )
    from poisson_ellipse_tpu.obs.static_cost import xla_cost
    from poisson_ellipse_tpu.solver.engine import BATCHED_ENGINES, build_solver

    if engine in BATCHED_ENGINES:
        raise ValueError(
            f"engine {engine!r} is lane-batched; the phase profile covers "
            "single-solve engines (throughput is the lanes protocol)"
        )
    if repeat < 1:
        raise ValueError("repeat must be >= 1")

    t0 = time.perf_counter()
    solver, args, engine = build_solver(problem, engine, dtype)
    fence(args)
    t_build = time.perf_counter() - t0

    # the cold-start phase: trace + XLA/Mosaic compile, AOT so the solve
    # phase below times pure execution of the same executable
    t0 = time.perf_counter()
    compiled = solver.lower(*args).compile()
    t_compile = time.perf_counter() - t0

    # H2D: re-stage the operands from host copies, fenced — what a
    # serving worker pays to place a request's operands
    host_args = [np.asarray(a) for a in args]
    t0 = time.perf_counter()
    dev_args = [jax.device_put(a) for a in host_args]
    fence(dev_args)
    t_h2d = time.perf_counter() - t0

    result = compiled(*dev_args)
    # warm-up fence outside every timed bracket (first dispatch may
    # still pay allocator work)
    fence(result)
    times = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        result = compiled(*dev_args)
        # the sync IS the measurement: each bracket must close on
        # completed device work (the TPU011 contract)
        fence(result)  # tpulint: disable=TPU008
        times.append(time.perf_counter() - t0)
    t_solve = statistics.median(times)

    t0 = time.perf_counter()
    w_host = np.asarray(result.w)
    t_d2h = time.perf_counter() - t0
    del w_host

    iters = int(result.iters)
    try:
        passes = passes_per_iter(problem, engine, dtype)
        modeled_bytes = modeled_hbm_bytes_per_iter(problem, engine, dtype)
    except ValueError:  # an engine without a traffic model stays profileable
        passes, modeled_bytes = None, None
    cost = xla_cost(solver, args) if with_xla_cost else None
    est_bytes = cost.get("bytes_accessed") if cost else None
    est_flops = cost.get("flops") if cost else None

    def rate(bytes_per_iter):
        if not bytes_per_iter or t_solve <= 0 or iters <= 0:
            return None
        return bytes_per_iter * iters / t_solve

    hbm = rate(modeled_bytes)
    hbm_xla = rate(est_bytes)
    flops = rate(est_flops)
    peak = hbm_peak_bytes_per_s()
    return {
        "engine": engine,
        "grid": [problem.M, problem.N],
        "dtype": jnp.dtype(dtype).name,
        "iters": iters,
        "converged": bool(result.converged),
        "t_build_s": round(t_build, 5),
        "t_compile_s": round(t_compile, 5),
        "t_h2d_s": round(t_h2d, 5),
        "t_solve_s": round(t_solve, 5),
        "t_d2h_s": round(t_d2h, 5),
        "us_per_iter": round(t_solve / iters * 1e6, 2) if iters else None,
        "modeled_passes_per_iter": passes,
        "modeled_hbm_bytes_per_iter": modeled_bytes,
        "est_hbm_bytes_per_iter": est_bytes,
        "hbm_gbps": round(hbm / 1e9, 3) if hbm else None,
        "hbm_gbps_xla": round(hbm_xla / 1e9, 3) if hbm_xla else None,
        "flops_per_s": round(flops, 1) if flops else None,
        "pct_of_model": (
            round(100.0 * est_bytes / modeled_bytes, 1)
            if est_bytes and modeled_bytes
            else None
        ),
        "hbm_peak_frac": (
            round(hbm / peak, 4) if hbm and peak else None
        ),
    }


def profile_table(
    problem: Problem,
    engines: tuple[str, ...] = ("xla",),
    dtype=jnp.float32,
    repeat: int = 3,
    with_xla_cost: bool = True,
) -> list[dict]:
    """One :func:`profile_engine` row per engine (skipping engines that
    refuse to build for this problem/dtype — a capacity-gated Pallas
    engine on the wrong part must not kill the table)."""
    rows = []
    for engine in engines:
        try:
            rows.append(
                profile_engine(
                    problem, engine, dtype, repeat=repeat,
                    with_xla_cost=with_xla_cost,
                )
            )
        except ValueError:
            # engine/dtype combination the registry rejects: skip the row
            continue
    return rows


def render_profile(rows) -> str:
    """The measured-vs-modeled roofline table (``harness diagnose``).

    Accepts one row or a list. The %-of-model column is XLA's own
    bytes-accessed estimate over the roofline traffic model's bytes —
    100% means the model still matches the compiled artifact.
    """
    if isinstance(rows, dict):
        rows = [rows]
    if not rows:
        return "profile: no engine produced a row"
    grid = rows[0]["grid"]
    lines = [
        f"phase profile {grid[0]}x{grid[1]} ({rows[0]['dtype']}, fenced "
        "wall clock; solve = median plain dispatch):",
        "  engine            compile      H2D    solve      D2H   "
        "us/iter   GB/s(model)  GB/s(XLA)  %of-model   MFLOP/s",
    ]
    for r in rows:
        def col(v, fmt="{:8.4f}", na="     n/a"):
            return fmt.format(v) if v is not None else na

        lines.append(
            f"  {r['engine']:<16s}"
            f" {col(r['t_compile_s'])}"
            f" {col(r['t_h2d_s'])}"
            f" {col(r['t_solve_s'])}"
            f" {col(r['t_d2h_s'])}"
            f"  {col(r['us_per_iter'], '{:8.1f}')}"
            f"     {col(r['hbm_gbps'], '{:9.2f}', '      n/a')}"
            f"  {col(r['hbm_gbps_xla'], '{:9.2f}', '      n/a')}"
            f"  {col(r['pct_of_model'], '{:8.1f}%', '     n/a ')}"
            f" {col(r['flops_per_s'] / 1e6 if r['flops_per_s'] else None, '{:9.1f}', '      n/a')}"
        )
    frac_rows = [r for r in rows if r.get("hbm_peak_frac") is not None]
    for r in frac_rows:
        lines.append(
            f"  {r['engine']}: {r['hbm_peak_frac']:.1%} of this part's "
            "HBM peak (traffic model)"
        )
    return "\n".join(lines)
