"""obs — the observability layer over the engine zoo.

Three legs, mirroring what a production solver service has to expose:

- :mod:`.convergence` — on-device per-iteration history (zr / diff /
  α / β) carried through the fused ``lax.while_loop`` as preallocated
  ring buffers: convergence curves with zero host syncs, surfaced as
  ``solve(..., history=True)`` on the classical, fused, pipelined and
  sharded engines.
- :mod:`.trace` + :mod:`.metrics` — dependency-free structured JSONL
  run tracing (run ids, monotonic phase spans, counters/gauges) behind
  ``--trace FILE`` / ``POISSON_TRACE=``; ``utils.timing.PhaseTimer`` is
  a thin shim over it.
- :mod:`.static_cost` — compile-time accounting from the jaxpr and
  XLA's cost analysis: psum/ppermute per iteration, estimated FLOPs and
  HBM bytes, measured-vs-modeled roofline columns — the layer that
  turns the pipelined engine's "1 collective/iter vs classical 2" claim
  into a regression-checked metric (``harness inspect``, BENCH
  artifacts).
- :mod:`.spectrum` — spectral diagnostics from the convergence trace:
  the Lanczos tridiagonal hiding in the recorded α/β, Ritz values,
  κ(M⁻¹A), the asymptotic CG rate, sharp iteration prediction and
  plateau detection (``harness diagnose``, the ``spectrum`` BENCH key).
- :mod:`.profile` — fenced compile/H2D/solve/D2H phase profiling with
  measured GB/s / FLOP/s joined against the static traffic model.
- :mod:`.export` — OpenMetrics text rendering of a metrics snapshot +
  atomic/periodic snapshot-to-file wiring (``--metrics FILE``).

:mod:`.static_cost` and :mod:`.profile` import the solver engines, so
they are intentionally NOT imported here — ``from poisson_ellipse_tpu.
obs import static_cost`` (or ``profile``) at use sites keeps this
package importable from inside the solver modules it instruments.
"""

from poisson_ellipse_tpu.obs.convergence import (
    HISTORY_FIELDS,
    ConvergenceTrace,
    history_init,
    history_record,
    trace_of,
)
from poisson_ellipse_tpu.obs.export import MetricsExporter, render_openmetrics
from poisson_ellipse_tpu.obs.metrics import (
    REGISTRY,
    Histogram,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
)
from poisson_ellipse_tpu.obs.spectrum import ritz_values, spectrum_report
from poisson_ellipse_tpu.obs.trace import Tracer, event, note, span, start, stop

__all__ = [
    "HISTORY_FIELDS",
    "ConvergenceTrace",
    "Histogram",
    "MetricsExporter",
    "MetricsRegistry",
    "REGISTRY",
    "Tracer",
    "counter",
    "event",
    "gauge",
    "histogram",
    "history_init",
    "history_record",
    "note",
    "render_openmetrics",
    "ritz_values",
    "span",
    "spectrum_report",
    "start",
    "stop",
    "trace_of",
]
