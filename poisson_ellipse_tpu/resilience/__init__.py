"""resilience — guarded solves, classified failures, fault injection.

Three legs, turning "the solver noticed something was wrong" into "the
service survived it":

- :mod:`.guard` — ``guarded_solve``: any engine's solve run in chunks
  with a one-word-per-chunk device-side health check (breakdown /
  NaN-Inf / stagnation) and a recovery ladder — true residual restart
  (direction-preserving, oracle-parity), f32→f64 precision escalation,
  engine fallback — capped by ``max_recoveries`` and classified on
  exhaustion.
- :mod:`.errors` — the :class:`SolveError` taxonomy and the harness
  exit-code contract (2 = diverged, 3 = oom, 4 = timeout), plus the one
  place device-runtime OOM strings are sniffed.
- :mod:`.faultinject` — deterministic fault injection (NaN into a named
  carry field at iteration k, forced breakdown, stagnation, halo-slab
  corruption, simulated OOM, checkpoint truncation, shrunken-VMEM
  capacity gates), so every recovery path is exercised in tests and via
  ``harness inject`` — never assumed.
"""

from poisson_ellipse_tpu.resilience.errors import (
    EXIT_DIVERGED,
    EXIT_OOM,
    EXIT_TIMEOUT,
    DivergedError,
    OutOfMemoryError,
    SolveError,
    SolveTimeout,
    classify_error,
    is_oom_error,
)
from poisson_ellipse_tpu.resilience.faultinject import (
    Fault,
    FaultPlan,
    corrupt_halo,
    force_breakdown,
    inject_nan,
    inject_stagnation,
    simulate_oom,
    simulated_vmem,
    truncate_latest_checkpoint,
)
from poisson_ellipse_tpu.resilience.guard import (
    HEALTH_BREAKDOWN,
    HEALTH_CONVERGED,
    HEALTH_NONFINITE,
    HEALTH_STAGNATION,
    GuardedResult,
    RecoveryEvent,
    guarded_solve,
    health_name,
)

__all__ = [
    "EXIT_DIVERGED",
    "EXIT_OOM",
    "EXIT_TIMEOUT",
    "DivergedError",
    "Fault",
    "FaultPlan",
    "GuardedResult",
    "HEALTH_BREAKDOWN",
    "HEALTH_CONVERGED",
    "HEALTH_NONFINITE",
    "HEALTH_STAGNATION",
    "OutOfMemoryError",
    "RecoveryEvent",
    "SolveError",
    "SolveTimeout",
    "classify_error",
    "corrupt_halo",
    "force_breakdown",
    "guarded_solve",
    "health_name",
    "inject_nan",
    "inject_stagnation",
    "is_oom_error",
    "simulate_oom",
    "simulated_vmem",
    "truncate_latest_checkpoint",
]
