"""resilience — guarded solves, classified failures, fault injection,
silent-corruption detection, degraded-mesh recovery.

Five legs, turning "the solver noticed something was wrong" into "the
service survived it":

- :mod:`.guard` — ``guarded_solve``: any engine's solve run in chunks
  with a one-word-per-chunk device-side health check (breakdown /
  NaN-Inf / stagnation) and a recovery ladder — true residual restart
  (direction-preserving, oracle-parity), f32→f64 precision escalation,
  engine fallback — capped by ``max_recoveries`` and classified on
  exhaustion.
- :mod:`.errors` — the :class:`SolveError` taxonomy and the harness
  exit-code contract (2 = diverged, 3 = oom, 4 = timeout), plus the one
  place device-runtime OOM strings are sniffed.
- :mod:`.faultinject` — deterministic fault injection (NaN into a named
  carry field at iteration k, forced breakdown, stagnation, halo-slab
  corruption, halo bit-flips, sign-flipped psums, simulated OOM /
  device loss / stragglers, checkpoint truncation, shrunken-VMEM
  capacity gates), so every recovery path is exercised in tests and via
  ``harness inject`` — never assumed.
- :mod:`.abft` — algorithm-based silent-corruption detection for the
  sharded engines: checksum/invariant partials riding the existing
  stacked convergence psum (1 psum/iter preserved), classified apart
  from breakdown and answered by rollback-and-rerun, with persistent
  corruption raising :class:`SilentCorruptionError` (exit 6).
- :mod:`.meshguard` — device-loss/straggler detection at chunk
  boundaries and degraded-mesh recovery: shrink the mesh over the
  survivors, re-shard the last durable checkpoint, resume
  (``elastic_solve``; exhaustion raises :class:`DeviceLossError`,
  exit 7).
"""

from poisson_ellipse_tpu.resilience.errors import (
    EXIT_DEVICE_LOSS,
    EXIT_DIVERGED,
    EXIT_FLEET_UNAVAILABLE,
    EXIT_OOM,
    EXIT_SDC,
    EXIT_TIMEOUT,
    DeviceLossError,
    DivergedError,
    FleetUnavailableError,
    OutOfMemoryError,
    SilentCorruptionError,
    SolveError,
    SolveTimeout,
    classify_error,
    is_device_loss_error,
    is_oom_error,
)
from poisson_ellipse_tpu.resilience.faultinject import (
    Fault,
    FaultPlan,
    corrupt_halo,
    device_loss,
    force_breakdown,
    halo_bitflip,
    inject_nan,
    inject_stagnation,
    lease_clock_skew,
    psum_corrupt,
    replica_hang,
    replica_kill,
    simulate_oom,
    simulated_vmem,
    straggler,
    truncate_latest_checkpoint,
)
from poisson_ellipse_tpu.resilience.guard import (
    HEALTH_BREAKDOWN,
    HEALTH_CONVERGED,
    HEALTH_NONFINITE,
    HEALTH_SDC,
    HEALTH_STAGNATION,
    GuardedResult,
    RecoveryEvent,
    guarded_solve,
    health_name,
)
from poisson_ellipse_tpu.resilience.meshguard import (
    ElasticResult,
    MeshEvent,
    elastic_solve,
)

__all__ = [
    "DeviceLossError",
    "ElasticResult",
    "EXIT_DEVICE_LOSS",
    "EXIT_DIVERGED",
    "EXIT_FLEET_UNAVAILABLE",
    "EXIT_OOM",
    "EXIT_SDC",
    "EXIT_TIMEOUT",
    "DivergedError",
    "Fault",
    "FaultPlan",
    "FleetUnavailableError",
    "GuardedResult",
    "HEALTH_BREAKDOWN",
    "HEALTH_CONVERGED",
    "HEALTH_NONFINITE",
    "HEALTH_SDC",
    "HEALTH_STAGNATION",
    "MeshEvent",
    "OutOfMemoryError",
    "RecoveryEvent",
    "SilentCorruptionError",
    "SolveError",
    "SolveTimeout",
    "classify_error",
    "corrupt_halo",
    "device_loss",
    "elastic_solve",
    "force_breakdown",
    "guarded_solve",
    "halo_bitflip",
    "health_name",
    "inject_nan",
    "inject_stagnation",
    "is_device_loss_error",
    "is_oom_error",
    "lease_clock_skew",
    "psum_corrupt",
    "replica_hang",
    "replica_kill",
    "simulate_oom",
    "simulated_vmem",
    "straggler",
    "truncate_latest_checkpoint",
]
