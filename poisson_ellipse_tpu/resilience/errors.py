"""Classified solve failures: one exception taxonomy, one exit-code contract.

The reference's failure story is a printf and a nonzero ``exit`` with no
taxonomy (``stage0/Withoutopenmp1.cpp:128`` prints "Breakdown" and
returns); the JAX runtime's is an opaque ``XlaRuntimeError`` whose only
machine-readable content is a status-prefixed message string. A serving
stack needs the middle layer: every way a guarded solve can fail maps to
exactly one :class:`SolveError` subclass, each carrying the process exit
code the harness CLI contracts to return:

  ========  ====================  ===========================================
  exit      class                 meaning
  ========  ====================  ===========================================
  2         DivergedError         recovery ladder exhausted: persistent
                                  breakdown / NaN poisoning / stagnation
  3         OutOfMemoryError      RESOURCE_EXHAUSTED with no engine left to
                                  degrade to
  4         SolveTimeout          ``--timeout`` deadline passed at a chunk
                                  boundary (partial trace artifact emitted)
  5         AdmissionRejected     the serving layer shed the request at
                                  admission (queue full / projected deadline
                                  miss); carries ``retry_after_s``
  ========  ====================  ===========================================

(exit 0 = converged, 1 = iteration cap reached without convergence — the
pre-existing harness contract — and the argparse-conventional 2 also
covers invalid invocations, which share "the request as stated cannot
succeed" with divergence.)

:func:`classify_error` is the single place device-runtime exceptions are
sniffed: XLA surfaces OOM as a ``RuntimeError`` whose message carries the
``RESOURCE_EXHAUSTED`` absl status (or "Out of memory"/"Allocation …
exceeds" phrasings, runtime-dependent), and Mosaic compile failures on an
over-budget kernel arrive the same way. Matching on the message is the
honest option — there is no structured error code on this API surface —
and it lives here exactly once so the guard, the engine chain and the
harness cannot drift.
"""

from __future__ import annotations

EXIT_DIVERGED = 2
EXIT_OOM = 3
EXIT_TIMEOUT = 4
EXIT_SHED = 5


class SolveError(RuntimeError):
    """Base of the classified solve failures.

    ``classification`` is the stable machine-readable tag (``diverged`` /
    ``oom`` / ``timeout``) used in trace events and JSON reports;
    ``exit_code`` the contracted process exit. ``iters`` is the last
    healthy iteration count the guard reached, so a caller can report
    how far the solve got before it was given up on.
    """

    classification = "error"
    exit_code = 1

    def __init__(self, message: str, iters: int | None = None):
        super().__init__(message)
        self.iters = iters


class DivergedError(SolveError):
    """Recovery ladder exhausted: the solve keeps producing breakdown,
    non-finite iterates, or no progress past ``max_recoveries``."""

    classification = "diverged"
    exit_code = EXIT_DIVERGED


class OutOfMemoryError(SolveError):
    """RESOURCE_EXHAUSTED at compile or run time with no smaller engine
    left on the capacity ladder to degrade to."""

    classification = "oom"
    exit_code = EXIT_OOM


class SolveTimeout(SolveError):
    """The per-solve deadline passed. Raised only at chunk boundaries —
    the in-flight chunk is allowed to complete, so the carry the guard
    holds (and any trace events already flushed) stay consistent."""

    classification = "timeout"
    exit_code = EXIT_TIMEOUT


class AdmissionRejected(SolveError):
    """The serving layer refused the request at admission: the bounded
    queue is full, or the projected wait already overruns the request's
    deadline (``serve.queue``). This is backpressure, not failure — the
    request was never dispatched and is safe to resubmit after
    ``retry_after_s`` (the load-shedding contract: reject loudly now
    rather than time out silently later)."""

    classification = "shed"
    exit_code = EXIT_SHED

    def __init__(self, message: str, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


# status phrasings XLA/Mosaic use for memory exhaustion, across runtime
# versions; matched case-sensitively (they are absl status spellings)
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
    "exceeds the memory capacity",
    "Attempting to allocate",
)


def is_oom_error(exc: BaseException) -> bool:
    """True when ``exc`` is a device memory-exhaustion failure."""
    if isinstance(exc, OutOfMemoryError):
        return True
    if isinstance(exc, MemoryError):
        return True
    text = str(exc)
    return any(marker in text for marker in _OOM_MARKERS)


def classify_error(exc: BaseException) -> str:
    """The classification tag for an arbitrary exception out of a solve
    dispatch: ``oom`` / ``timeout`` / ``diverged`` (already-classified
    SolveErrors keep their own tag) or ``unknown`` for everything else —
    unknowns must stay loud, never be swallowed into a retry loop."""
    if isinstance(exc, SolveError):
        return exc.classification
    if is_oom_error(exc):
        return "oom"
    return "unknown"
