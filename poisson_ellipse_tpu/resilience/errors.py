"""Classified solve failures: one exception taxonomy, one exit-code contract.

The reference's failure story is a printf and a nonzero ``exit`` with no
taxonomy (``stage0/Withoutopenmp1.cpp:128`` prints "Breakdown" and
returns); the JAX runtime's is an opaque ``XlaRuntimeError`` whose only
machine-readable content is a status-prefixed message string. A serving
stack needs the middle layer: every way a guarded solve can fail maps to
exactly one :class:`SolveError` subclass, each carrying the process exit
code the harness CLI contracts to return:

  ========  ======================  =========================================
  exit      class                   meaning
  ========  ======================  =========================================
  2         DivergedError           recovery ladder exhausted: persistent
                                    breakdown / NaN poisoning / stagnation
  3         OutOfMemoryError        RESOURCE_EXHAUSTED with no engine left to
                                    degrade to
  4         SolveTimeout            ``--timeout`` deadline passed at a chunk
                                    boundary (partial trace artifact emitted)
  5         AdmissionRejected       the serving layer shed the request at
                                    admission (queue full / projected deadline
                                    miss); carries ``retry_after_s``
  6         SilentCorruptionError   the ABFT checksum/invariant layer
                                    (``resilience.abft``) detected silent
                                    data corruption that a rollback-and-rerun
                                    could not clear (a persistent SDC source:
                                    failing HBM, a sick interconnect lane)
  7         DeviceLossError         a mesh device was lost and no degraded
                                    mesh remains to resume on (or the
                                    degradation budget is exhausted)
  8         InvalidGeometryError    the geometry admissibility gate
                                    (``geom.validate``) rejected the problem
                                    BEFORE any device loop ran: malformed
                                    spec, empty/under-resolved domain,
                                    boundary contact, or an assembled
                                    operator that fails the finite/M-matrix/
                                    SPD checks
  9         FleetUnavailableError   every scheduler replica of the serving
                                    fleet (``fleet.router``) is dead or
                                    draining: there is no admission path
                                    left, so the request is refused loudly
                                    (with ``retry_after_s``) instead of
                                    hanging on a queue nobody will drain
  ========  ======================  =========================================

(exit 0 = converged, 1 = iteration cap reached without convergence — the
pre-existing harness contract — and the argparse-conventional 2 also
covers invalid invocations, which share "the request as stated cannot
succeed" with divergence.)

:func:`classify_error` is the single place device-runtime exceptions are
sniffed: XLA surfaces OOM as a ``RuntimeError`` whose message carries the
``RESOURCE_EXHAUSTED`` absl status (or "Out of memory"/"Allocation …
exceeds" phrasings, runtime-dependent), and Mosaic compile failures on an
over-budget kernel arrive the same way. Matching on the message is the
honest option — there is no structured error code on this API surface —
and it lives here exactly once so the guard, the engine chain and the
harness cannot drift.
"""

from __future__ import annotations

EXIT_DIVERGED = 2
EXIT_OOM = 3
EXIT_TIMEOUT = 4
EXIT_SHED = 5
EXIT_SDC = 6
EXIT_DEVICE_LOSS = 7
EXIT_INVALID_GEOMETRY = 8
EXIT_FLEET_UNAVAILABLE = 9


class SolveError(RuntimeError):
    """Base of the classified solve failures.

    ``classification`` is the stable machine-readable tag (``diverged`` /
    ``oom`` / ``timeout``) used in trace events and JSON reports;
    ``exit_code`` the contracted process exit. ``iters`` is the last
    healthy iteration count the guard reached, so a caller can report
    how far the solve got before it was given up on.
    """

    classification = "error"
    exit_code = 1

    def __init__(self, message: str, iters: int | None = None):
        super().__init__(message)
        self.iters = iters


class DivergedError(SolveError):
    """Recovery ladder exhausted: the solve keeps producing breakdown,
    non-finite iterates, or no progress past ``max_recoveries``."""

    classification = "diverged"
    exit_code = EXIT_DIVERGED


class OutOfMemoryError(SolveError):
    """RESOURCE_EXHAUSTED at compile or run time with no smaller engine
    left on the capacity ladder to degrade to."""

    classification = "oom"
    exit_code = EXIT_OOM


class SolveTimeout(SolveError):
    """The per-solve deadline passed. Raised only at chunk boundaries —
    the in-flight chunk is allowed to complete, so the carry the guard
    holds (and any trace events already flushed) stay consistent."""

    classification = "timeout"
    exit_code = EXIT_TIMEOUT


class AdmissionRejected(SolveError):
    """The serving layer refused the request at admission: the bounded
    queue is full, or the projected wait already overruns the request's
    deadline (``serve.queue``). This is backpressure, not failure — the
    request was never dispatched and is safe to resubmit after
    ``retry_after_s`` (the load-shedding contract: reject loudly now
    rather than time out silently later)."""

    classification = "shed"
    exit_code = EXIT_SHED

    def __init__(self, message: str, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class SilentCorruptionError(SolveError):
    """The ABFT layer (``resilience.abft``) caught silent data corruption
    — a checksum/invariant violation in the sharded solve's own algebra
    (Huang–Abraham stencil checksum, residual/iterate sum recurrences,
    ⟨r, z⟩ positivity) — and the rollback-and-rerun recovery did not
    clear it: the corruption re-fired from the same clean carry, which is
    the signature of a *persistent* SDC source (failing HBM bank, sick
    interconnect lane), not a transient flip. Raised instead of returning
    an iterate the corruption may have laundered into; the guard NEVER
    applies residual replacement to an SDC-flagged carry for exactly that
    reason."""

    classification = "sdc"
    exit_code = EXIT_SDC


class DeviceLossError(SolveError):
    """A mesh device was lost (or declared lost by the straggler
    deadline) and the degraded-mesh ladder has nowhere left to go: no
    surviving devices, or ``max_degrades`` successive shrinks already
    spent. Anything short of this is *recovered*, not raised — the mesh
    guard rebuilds a smaller mesh from the last durable checkpoint and
    resumes (``resilience.meshguard``)."""

    classification = "device-loss"
    exit_code = EXIT_DEVICE_LOSS


class InvalidGeometryError(SolveError):
    """The geometry admissibility gate (``geom.validate``) classified the
    *problem* — not the solver — as unsolvable as stated, before any
    device loop ran. ``reason`` is the stable machine-readable sub-tag:

      ``malformed-spec``        the JSON geometry spec does not parse into
                                an SDF tree (unknown kind, wrong arity,
                                non-finite parameter)
      ``sdf-nonfinite``         the SDF itself evaluates to NaN/Inf on Ω
      ``empty-domain``          no sample of Ω lies inside the domain
      ``under-resolved``        the domain exists but a feature is thinner
                                than the grid spacing h — invisible to the
                                node lattice, so the discrete solve would
                                silently answer a different question
      ``boundary-contact``      the domain touches the Dirichlet ring of Ω
                                (the fictitious-domain method needs the
                                penalty band strictly around D)
      ``operator-nonfinite``    assembled coefficients carry NaN/Inf
      ``operator-not-m-matrix`` a face coefficient is <= 0 where the
                                5-point M-matrix sign structure needs > 0
      ``operator-asymmetric``   <Au, v> != <u, Av> beyond f64 round-off
      ``operator-not-spd``      the host Lanczos probe (``obs.spectrum``
                                over a short f64 diag-PCG) found a
                                non-positive Ritz value / indefinite pivot

    Serving maps it to the terminal ``invalid`` outcome at ADMISSION —
    a bad geometry is rejected before it can poison a lane mid-batch."""

    classification = "invalid-geometry"
    exit_code = EXIT_INVALID_GEOMETRY

    def __init__(self, message: str, reason: str = "invalid"):
        super().__init__(message)
        self.reason = reason


class FleetUnavailableError(SolveError):
    """Every scheduler replica of the serving fleet is down (dead lease,
    fenced, or draining): the router has no admission path left. This is
    the fleet-wide analog of :class:`AdmissionRejected` — refused loudly
    NOW with a ``retry_after_s`` hint, never a request parked on a queue
    no surviving replica will ever drain. Anything short of total loss is
    *routed around*, not raised: a single dead replica's queued and
    in-flight requests are handed off to survivors
    (``fleet.handoff``)."""

    classification = "fleet-unavailable"
    exit_code = EXIT_FLEET_UNAVAILABLE

    def __init__(self, message: str, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class LeaseStoreError(RuntimeError):
    """Base of the lease-store (``fleet.replica.LeaseStore``) failure
    taxonomy. These are *infrastructure* errors, not solve errors: they
    never escape the fleet router to a caller. The router converts
    "store unreachable past the grace window" into a classified
    :class:`FleetUnavailableError` (exit 9) at the admission boundary —
    fail-safe, never a hang — and everything else into deferred work
    that completes when the store recovers. ``classification`` is the
    tag used in trace events."""

    classification = "lease-store"


class LeaseStoreOutageError(LeaseStoreError):
    """The lease store is unreachable (injected partition/outage, or a
    real backend refusing the round-trip). Replicas holding unexpired
    leases keep serving — epoch *validation* answers from the local
    cache mirror — but every operation that must round-trip (issuing a
    fresh incarnation, fencing a dead one) raises this until the store
    answers a ping again."""

    classification = "lease-store-outage"


class LeaseStoreCorruptError(LeaseStoreError):
    """The persisted lease-store state failed to parse (torn write,
    truncation, bit rot). Classified loudly instead of re-initialising
    the epoch table: silently resetting epochs would let a fenced
    zombie's stale token validate again — the textbook split-brain."""

    classification = "lease-store-corrupt"


# status phrasings XLA/Mosaic use for memory exhaustion, across runtime
# versions; matched case-sensitively (they are absl status spellings)
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
    "exceeds the memory capacity",
    "Attempting to allocate",
)


def is_oom_error(exc: BaseException) -> bool:
    """True when ``exc`` is a device memory-exhaustion failure."""
    if isinstance(exc, OutOfMemoryError):
        return True
    if isinstance(exc, MemoryError):
        return True
    text = str(exc)
    return any(marker in text for marker in _OOM_MARKERS)


# status phrasings the runtime uses when a device dies under a dispatch;
# same stance as the OOM markers — the message string is the only
# machine-readable surface this API exposes. The simulated form
# (faultinject.SimulatedDeviceLoss) carries the first marker verbatim.
_DEVICE_LOSS_MARKERS = (
    "DEVICE_LOST",
    "device is in an error state",
    "Device or resource busy",
    "DATA_LOSS",
)


def is_device_loss_error(exc: BaseException) -> bool:
    """True when ``exc`` reads as a lost/failed device under a dispatch
    (real runtime phrasings or the injected
    ``faultinject.SimulatedDeviceLoss``)."""
    if isinstance(exc, DeviceLossError):
        return True
    text = str(exc)
    return any(marker in text for marker in _DEVICE_LOSS_MARKERS)


def classify_error(exc: BaseException) -> str:
    """The classification tag for an arbitrary exception out of a solve
    dispatch: ``oom`` / ``timeout`` / ``diverged`` (already-classified
    SolveErrors keep their own tag) or ``unknown`` for everything else —
    unknowns must stay loud, never be swallowed into a retry loop."""
    if isinstance(exc, SolveError):
        return exc.classification
    if isinstance(exc, LeaseStoreError):
        return exc.classification
    if is_oom_error(exc):
        return "oom"
    if is_device_loss_error(exc):
        return "device-loss"
    return "unknown"
