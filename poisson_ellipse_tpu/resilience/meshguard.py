"""Degraded-mesh recovery: survive the hardware the solve runs on.

``resilience.guard`` recovers *numerical* failure on a healthy mesh;
this module recovers the mesh itself. The reference's MPI stages die
wholesale when any rank fails (``MPI_Init``/``Finalize`` with no
recovery surface — ``parallel.multihost``); at pod scale device loss
and stragglers are routine, so the serving north star needs the ladder
this module is:

1. **Detect at chunk boundaries.** The dispatched chunk is the unit of
   failure: a lost device surfaces as a classified dispatch error
   (``errors.is_device_loss_error`` — real runtime phrasings or the
   injected ``SimulatedDeviceLoss``), a straggler as a chunk that blows
   the per-chunk deadline (``chunk_deadline_s`` — the hedge policy: a
   device too slow IS lost, capacity-wise). ABFT silent-corruption flags
   (``abft=True``) are read at the same boundary and answered with
   reload-from-checkpoint + re-run — the durable form of the guard's
   rollback — before any corrupted carry can be checkpointed.
2. **Durable state, elastic layout.** Every chunk boundary saves the
   classical 8-field carry through ``solver.checkpoint`` (orbax commit +
   integrity manifests + quarantine — the PR 4 machinery, unchanged).
   The checkpoint fingerprints its mesh SHAPE, and resuming onto a
   different shape re-shards instead of refusing: crop the dead mesh's
   padding, re-pad to the survivors' decomposition, lay out, continue
   (``parallel.elastic``; the reshard parity case is pinned in
   ``tests/test_checkpoint.py``).
3. **Shrink and resume.** On detection: emit a ``degrade:mesh`` trace
   event, rebuild a near-square mesh over the surviving devices
   (``parallel.elastic.shrink_mesh``), restore the last durable step,
   and keep solving. ``max_degrades`` successive shrinks (or an empty
   survivor set) raise the classified
   :class:`~poisson_ellipse_tpu.resilience.errors.DeviceLossError` —
   never a hang, never a silent partial result.

Solution parity is the contract: a 2×2 solve killed mid-flight and
finished on 1×2 reaches the same l2-vs-analytic error as an
uninterrupted run (decomposition changes only psum reduction grouping —
ulp-scale — plus at most one chunk of replayed iterations), pinned in
``tests/test_elastic.py``.

The serving layer composes differently — a scheduler's in-flight batch
carry is disposable, so ``serve.scheduler`` answers device loss by
re-entering every in-flight request through the journal/retry ladder
(chaos-tested in ``serve.chaos`` mesh-kill drills) — but both rest on
the same detection and classification here.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.resilience.abft import (
    SDC as _SDC,
    abft_dummy_tail,
)
from poisson_ellipse_tpu.resilience.errors import (
    DeviceLossError,
    SilentCorruptionError,
    classify_error,
)
from poisson_ellipse_tpu.resilience.faultinject import FaultPlan
from poisson_ellipse_tpu.solver.pcg import PCGResult

# classical sharded carry addressing (the meshguard drives the classical
# stepper; the guard's engine zoo handles the rest; the ABFT shadow tail
# is addressed through resilience.abft's layout constants)
_FIELDS = {"w": 1, "r": 2, "p": 3, "zr": 4}
_BD, _ZR = 7, 4

DEFAULT_CHUNK = 64


@dataclasses.dataclass(frozen=True)
class MeshEvent:
    """One mesh-level action: what was detected and what the guard did."""

    kind: str       # degrade:mesh / sdc-rollback
    at_iter: int
    cause: str      # device-loss / straggler-deadline / abft
    mesh_before: tuple[int, int]
    mesh_after: tuple[int, int]


@dataclasses.dataclass
class ElasticResult:
    """A mesh-guarded solve's outcome: the PCGResult, the degradation
    story (empty ``events`` = the original mesh survived), and the mesh
    shape that actually finished the solve."""

    result: PCGResult
    events: tuple
    mesh_shape: tuple[int, int]
    degrades: int


def _mesh_shape(mesh) -> tuple[int, int]:
    from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y

    return (mesh.shape[AXIS_X], mesh.shape[AXIS_Y])


def elastic_solve(
    problem: Problem,
    mesh=None,
    dtype=jnp.float32,
    *,
    directory: str,
    chunk: int = DEFAULT_CHUNK,
    abft: bool = False,
    chunk_deadline_s: Optional[float] = None,
    faults: Optional[FaultPlan] = None,
    max_degrades: int = 2,
) -> ElasticResult:
    """Solve on ``mesh`` with device-loss/straggler detection and
    degraded-mesh recovery (module docstring). ``directory`` holds the
    durable checkpoints every chunk boundary writes — it IS the recovery
    point, so give it a filesystem that survives the devices.

    ``chunk_deadline_s`` arms straggler detection: a chunk whose
    dispatch (fenced) overruns it degrades the mesh exactly like a
    loss, excluding the straggling device when the fault plan names one
    (real deployments name it from runtime telemetry) and the
    highest-index device otherwise — the hedge policy.
    """
    from poisson_ellipse_tpu.parallel.elastic import shrink_mesh
    from poisson_ellipse_tpu.parallel.mesh import make_mesh
    from poisson_ellipse_tpu.parallel.pcg_sharded import (
        build_sharded_recover,
        build_sharded_stepper,
        sharded_result_of,
    )
    from poisson_ellipse_tpu.solver.checkpoint import CheckpointingSolver

    if mesh is None:
        mesh = make_mesh()
    plan = faults if faults is not None else FaultPlan()
    events: list[MeshEvent] = []
    degrades = 0
    sdc_strikes = 0
    max_iter = problem.max_iterations

    while True:  # one pass per mesh incarnation
        shape = _mesh_shape(mesh)
        store = CheckpointingSolver(
            problem, directory, chunk=chunk, dtype=dtype, mesh=mesh
        )
        try:
            # one stepper build per MESH INCARNATION is the degraded-mesh
            # ladder itself (bounded by max_degrades), not a hot-loop
            # retrace — the chunk loop below reuses these compiled fns
            init_fn, advance_fn = build_sharded_stepper(
                problem, mesh, dtype, abft=abft  # tpulint: disable=TPU013
            )
            restored = store.restore_latest()
            if restored is None:
                state = init_fn()
            elif abft:
                # the restored 8-field carry needs its shadow scalars
                # re-anchored against THIS mesh's reductions: the
                # recover primitive rebuilds r from ground truth and
                # anchors in one off-hot-path dispatch
                # (per-incarnation, like the stepper above)
                recover_fn = build_sharded_recover(
                    problem, mesh, dtype, abft=True  # tpulint: disable=TPU013
                )
                state = recover_fn(
                    tuple(restored) + abft_dummy_tail(dtype)
                )
            else:
                state = tuple(restored)

            lost: list[int] = []
            cause = None
            # the first chunk on a (re)built mesh pays trace+compile:
            # the straggler deadline judges steady-state dispatches only
            compile_chunk = True
            while True:  # chunk loop on this mesh
                k = int(state[0])
                if bool(state[6]) or bool(state[7]) or k >= max_iter:
                    result = sharded_result_of(problem, state[:8])
                    return ElasticResult(
                        result=result,
                        events=tuple(events),
                        mesh_shape=shape,
                        degrades=degrades,
                    )
                stop = plan.next_stop(k - 1)
                limit = min(k + chunk, max_iter)
                if stop is not None and k < stop:
                    limit = min(limit, stop)
                t0 = time.monotonic()
                # dispatch-level faults (device_loss raises, straggler
                # sleeps) and carry-level SDC faults fire here, exactly
                # at the boundary — the guard's injection contract
                run_state = plan.apply(
                    k, state, _FIELDS, _BD, _ZR
                ) if plan else state
                new = advance_fn(run_state, limit)
                jax.block_until_ready(new)  # the deadline needs a fence
                elapsed = time.monotonic() - t0
                was_compile_chunk, compile_chunk = compile_chunk, False
                if (
                    chunk_deadline_s is not None
                    and not was_compile_chunk
                    and elapsed > chunk_deadline_s
                ):
                    # only devices still IN this mesh count as an
                    # attribution — earlier degrades already removed
                    # theirs, and excluding a gone device would burn a
                    # degrade on an identical mesh
                    present = {d.id for d in mesh.devices.flat}
                    lost = [
                        d for d in plan.lost_devices() if d in present
                    ] or [max(present)]
                    cause = "straggler-deadline"
                    break
                if abft and bool(new[_SDC]):
                    # silent corruption flagged: the durable checkpoint
                    # is the rollback point — reload it and re-run the
                    # chunk; NEVER checkpoint the flagged carry. A
                    # re-fire from the clean reload is persistent
                    # hardware: classified error.
                    sdc_strikes += 1
                    if sdc_strikes > 1:
                        raise SilentCorruptionError(
                            "silent corruption re-detected after a "
                            f"clean reload at iteration ~{k} — "
                            "persistent SDC source under this mesh",
                            iters=k,
                        )
                    obs_trace.event(
                        "recovery:sdc-rollback", iter=k, engine="xla",
                        detail="meshguard: reload last checkpoint + rerun",
                    )
                    events.append(MeshEvent(
                        "sdc-rollback", k, "abft", shape, shape
                    ))
                    reloaded = store.restore_latest()
                    if reloaded is None:
                        state = init_fn()
                    else:
                        # a rare recovery action, bounded by the
                        # sdc_strikes budget above, not a hot retrace
                        recover_fn = build_sharded_recover(
                            problem,
                            mesh,  # tpulint: disable=TPU013
                            dtype,
                            abft=True,
                        )
                        state = recover_fn(
                            tuple(reloaded) + abft_dummy_tail(dtype)
                        )
                    continue
                sdc_strikes = 0
                state = new
                store.save(state)
        except Exception as e:  # noqa: BLE001 — classified; unknowns re-raised
            if classify_error(e) != "device-loss":
                raise
            present = {d.id for d in mesh.devices.flat}
            named = getattr(e, "device", None)
            lost = [named] if named in present else [
                d for d in plan.lost_devices() if d in present
            ]
            if not lost:
                lost = [max(present)]
            cause = "device-loss"
        finally:
            store.close()

        # ---- degrade: shrink the mesh and resume from the checkpoint ----
        degrades += 1
        if degrades > max_degrades:
            raise DeviceLossError(
                f"mesh degraded {degrades - 1} time(s) already and "
                f"{cause} struck again — degradation budget exhausted",
                iters=None,
            )
        new_mesh = shrink_mesh(mesh, lost)
        obs_trace.event(
            "degrade:mesh",
            cause=cause,
            lost_devices=sorted(lost),
            from_mesh=list(shape),
            to_mesh=list(_mesh_shape(new_mesh)),
        )
        events.append(MeshEvent(
            "degrade:mesh", 0, cause, shape, _mesh_shape(new_mesh)
        ))
        mesh = new_mesh
