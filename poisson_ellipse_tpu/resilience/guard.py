"""Guarded solves: failure as a handled state, not a wrong answer.

Every engine in the zoo *detects* failure — the ``breakdown`` flag in the
PCG carry stops the loop, a NaN propagates until an oracle notices — but
none recovers. This module wraps any registered engine in a guard that
runs the solve in chunks of K iterations (the checkpoint chunking
machinery, which already proves chunk boundaries do not change the
arithmetic) and, between chunks, reads a SINGLE device-side health word:

  bit 0  breakdown   the carry's (Ap, p) < 1e-15 exit fired
  bit 1  nonfinite   NaN/Inf anywhere in the carry's vectors or scalars
  bit 2  stagnation  a full chunk ran and neither zr nor the step norm
                     improved (the drifted-recurrence failure the
                     pipelined literature's residual replacement exists
                     for)
  bit 3  converged   the loop's own stopping rule fired

The zero-host-syncs-per-iteration invariant is preserved: the traced
chunk is byte-for-byte the production ``advance`` loop (jaxpr-pinned in
``tests/test_resilience.py`` — zero overhead when healthy), and the
health word is one extra tiny dispatch plus one ``int()`` per chunk —
off the per-iteration hot path by construction.

On an unhealthy chunk the guard applies a recovery ladder:

1. **True residual restart** — from the last trustworthy iterate
   (breakdown keeps its own pre-update carry; NaN/stagnation roll back
   to the previous healthy chunk boundary), rebuild the recurrence state
   from ground truth: ``r = rhs − A·w``, fresh preconditioned residual,
   fresh ``zr`` — KEEPING the search direction ``p``. Keeping ``p`` is
   load-bearing: it is exactly the fixed-cadence residual replacement
   ``ops.pipelined_pcg`` already performs (Ghysels–Vanroose §4.3), which
   preserves the Krylov direction and with it oracle iteration parity
   (measured: restart-with-p reconverges in the clean run's exact count;
   a full ``p = z`` restart costs ~25% more iterations).
2. **Precision escalation** — on the xla-stencil path with f32/bf16 and
   ``jax_enable_x64`` on, recast the carry and operands to f64 and
   restart there: round-off-driven breakdown and stagnation are f32
   phenomena (the pipelined module's measured spurious-breakdown note).
3. **Engine fallback** — pipelined → classical (the direction ``p`` and
   iterate carry over; the classical recurrence has no drift to manage),
   pallas → xla stencil. RESOURCE_EXHAUSTED at dispatch takes this rung
   directly — a restart cannot fix an OOM.

Every recovery emits an ``obs.trace`` ``recovery:*`` event and counts
against ``max_recoveries``; exhaustion raises the classified
:class:`~poisson_ellipse_tpu.resilience.errors.SolveError` (never a NaN
result dressed up as a converged ``PCGResult``). The VMEM mega-kernel
engines (resident/streamed/xl — scalar state lives in kernel scratch, so
there is no carry to chunk) are guarded at whole-solve granularity: the
result is health-checked and failures degrade down the capacity ladder
resident → streamed → xl → guarded xla.

Faults are injectable at exact iterations via
:class:`~poisson_ellipse_tpu.resilience.faultinject.FaultPlan` — the
recovery paths are exercised, not assumed (``harness inject``,
``tests/test_resilience.py``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.reduction import grid_dot
from poisson_ellipse_tpu.ops.stencil import apply_a, apply_dinv, diag_d
from poisson_ellipse_tpu.resilience.abft import (
    SDC as ABFT_SDC,
    abft_dummy_tail as _abft_dummy_tail,
)
from poisson_ellipse_tpu.resilience.errors import (
    DivergedError,
    OutOfMemoryError,
    SilentCorruptionError,
    SolveError,
    SolveTimeout,
    classify_error,
)
from poisson_ellipse_tpu.resilience.faultinject import FaultPlan
from poisson_ellipse_tpu.solver.pcg import PCGResult

HEALTH_BREAKDOWN = 1
HEALTH_NONFINITE = 2
HEALTH_STAGNATION = 4
HEALTH_CONVERGED = 8
# bit 4: the ABFT checksum/invariant layer flagged silent corruption
# inside the chunk (resilience.abft; sharded engines with abft=True).
# Routed NOT into the restart ladder but into rollback-and-rerun — a
# residual-replacement restart would launder the corrupted iterate.
HEALTH_SDC = 16

_UNHEALTHY = HEALTH_BREAKDOWN | HEALTH_NONFINITE | HEALTH_STAGNATION

# single-chip capacity ladder the whole-solve guard degrades down; the
# last rung is the chunked guarded xla loop, which has no capacity gate
_CAPACITY_LADDER = ("resident", "streamed", "xl")

DEFAULT_CHUNK = 128

# Convergence-claim verification: a drifted recurrence can satisfy the
# step-norm stopping rule with a garbage iterate (measured: corrupting
# the pipelined carry's s gives diff ~ 1e-16 at an iterate nowhere near
# the solution — the silent wrong answer). Before the guard accepts a
# converged chunk it checks ‖r_carried − (rhs − A·w)‖ / ‖rhs‖: healthy
# recurrences track the true residual to accumulated round-off (≲1e-6
# relative at convergence, f32), drifted ones miss by orders of
# magnitude. One extra dispatch at the FINAL chunk only — the
# per-iteration loop is untouched.
RESIDUAL_DRIFT_TOL = 1e-2


@dataclasses.dataclass(frozen=True)
class RecoveryEvent:
    """One recovery action: what fired, where, and what the guard did."""

    kind: str  # residual-restart / precision-escalation / engine-fallback
    at_iter: int
    health: int
    engine: str
    detail: str = ""


class GuardedResult(NamedTuple):
    """A guarded solve's outcome: the PCGResult plus the recovery story
    (empty ``recoveries`` = the healthy path ran start to finish) and
    the engine/dtype that actually finished the solve (they differ from
    the request after an escalation or fallback)."""

    result: PCGResult
    recoveries: tuple[RecoveryEvent, ...]
    engine: str
    dtype: str


def health_name(word: int) -> str:
    """Human label for a health word's unhealthy bits."""
    names = []
    if word & HEALTH_BREAKDOWN:
        names.append("breakdown")
    if word & HEALTH_NONFINITE:
        names.append("nonfinite")
    if word & HEALTH_STAGNATION:
        names.append("stagnation")
    if word & HEALTH_SDC:
        names.append("sdc")
    return "+".join(names) or "healthy"


def _health_word(vectors, zr, diff, k, converged, breakdown, zr_prev,
                 diff_prev, limit):
    """The packed int32 health word — shared by every adapter. Pure
    array ops over the carry; the guard reads ONE host int per chunk."""
    finite = jnp.asarray(True)
    for v in vectors:
        finite = finite & jnp.all(jnp.isfinite(v))
    finite = finite & jnp.isfinite(zr) & ~jnp.isnan(diff)
    # no progress over a full chunk (neither zr nor the step norm
    # improved), or a non-positive zr — (z, r) is an energy inner
    # product, strictly positive for the SPD operator until convergence;
    # zr ≤ 0 means the recurrence no longer describes the system
    stalled = (
        (k == limit)
        & ~converged
        & ~breakdown
        & (zr >= zr_prev)
        & (diff >= diff_prev)
    ) | (~converged & ~breakdown & (zr <= 0))
    return (
        breakdown.astype(jnp.int32) * HEALTH_BREAKDOWN
        + (~finite).astype(jnp.int32) * HEALTH_NONFINITE
        + stalled.astype(jnp.int32) * HEALTH_STAGNATION
        + converged.astype(jnp.int32) * HEALTH_CONVERGED
    )


def _cast_carry(state, dtype):
    """Recast a carry's floating fields (precision escalation); integer
    counters and boolean flags pass through unchanged."""
    out = []
    for x in state:
        x = jnp.asarray(x)
        out.append(x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x)
    return tuple(out)


# --------------------------------------------------------------------------
# engine adapters: one duck-typed chunk/health/recover interface per carry
# --------------------------------------------------------------------------


class _ClassicalAdapter:
    """The classical single-chip carry (``solver.pcg``), xla or pallas
    stencil. Carry layout (k, w, r, p, zr, diff, converged, breakdown).

    ``precond_kind`` ("mg" / "cheb") runs the same carry with the
    multigrid V-cycle / Chebyshev preconditioner (``mg.engine``) in the
    ``z = M⁻¹ r`` slot — the guard's chunk/health/recover machinery is
    preconditioner-agnostic because the carry layout is. Its fallback
    ladder is mg-pcg → cheb-pcg → diag classical: a V-cycle poisoned by
    a NaN in a coarse level degrades to the polynomial rung, then to
    the reference preconditioner that every oracle is pinned against.

    ``sstep_s`` (2 or 4) swaps the advance for the s-step recurrence
    (``ops.sstep_pcg`` — the carry layout is deliberately identical),
    engine name "sstep"/"sstep-pallas"; its fallback is
    sstep → pipelined (carry handoff, ``_to_pipelined``) → classical.

    ``storage_dtype`` (``ops.precision``) runs the narrow-storage loop;
    the adapter's escalation then has a rung BELOW f64 — *storage
    promotion* back to compute width (``promote``), which the guard
    also applies on convergence/progress-stall so a narrow solve always
    FINISHES at full width (accuracy recovered, not hoped).
    """

    FIELDS = {"w": 1, "r": 2, "p": 3, "zr": 4}
    K, ZR, DIFF, CONV, BD = 0, 4, 5, 6, 7

    def __init__(self, problem: Problem, dtype, stencil: str = "xla",
                 interpret=None, operands=None, precond_kind=None,
                 precond_config=None, geometry=None, theta=None,
                 storage_dtype=None, sstep_s=None, x0=None):
        from poisson_ellipse_tpu.ops.precision import resolve_storage_dtype
        from poisson_ellipse_tpu.solver.pcg import (
            advance as pcg_advance,
            init_state as pcg_init_state,
        )

        self.problem = problem
        self.dtype = dtype
        self.stencil = stencil
        self.interpret = interpret
        self.precond_kind = precond_kind
        self.geometry = geometry
        self.theta = theta
        self.storage_dtype = resolve_storage_dtype(storage_dtype, dtype)
        self.sstep_s = sstep_s
        self._precond_cfg = None
        if precond_kind is not None:
            from poisson_ellipse_tpu.solver.engine import (
                PRECOND_ENGINE_BY_KIND,
            )

            self.engine = PRECOND_ENGINE_BY_KIND[precond_kind]
        elif sstep_s is not None:
            self.engine = "sstep" if stencil == "xla" else "sstep-pallas"
        else:
            self.engine = "xla" if stencil == "xla" else "pallas"
        a, b, rhs = (
            operands if operands is not None
            else assembly.assemble(problem, dtype, geometry=geometry,
                                   theta=theta)
        )
        self._operands = (a, b, rhs)
        if precond_kind is not None:
            from poisson_ellipse_tpu.mg.engine import make_precond

            # operands are shared so the build never re-assembles; the
            # fallback path also hands the already-resolved spectral
            # interval over (precond_config), skipping a second probe
            factory, self._precond_cfg = make_precond(
                problem, dtype, precond_kind, config=precond_config,
                operands=(a, b, rhs), geometry=geometry, theta=theta,
            )
            precond = factory(a, b)
        else:
            precond = None
        self.rhs_norm = float(jnp.sqrt(jnp.sum(rhs.astype(jnp.float32) ** 2)))
        st = self.storage_dtype
        # ``x0`` warm-starts the chunked carry (w = x0, TRUE residual):
        # the full-multigrid handoff's seed — the guard then chunk-steps,
        # health-checks and recovers the verification loop exactly like
        # mg-pcg, and every recover() rebuild keeps the iterate (and with
        # it the F-cycle's head start)
        self._init = lambda: pcg_init_state(
            problem, a, b, rhs, precond=precond, storage_dtype=st, x0=x0
        )
        # the raw chunk closure IS the production advance — exposed
        # unjitted so tests can pin the guarded jaxpr against it
        if sstep_s is not None:
            from poisson_ellipse_tpu.ops.sstep_pcg import (
                advance as sstep_advance,
            )

            self.advance_fn = lambda state, limit: sstep_advance(
                problem, a, b, rhs, state, s=sstep_s, limit=limit,
                stencil=stencil, interpret=interpret, storage_dtype=st,
            )
        else:
            self.advance_fn = lambda state, limit: pcg_advance(
                problem, a, b, rhs, state, limit=limit, stencil=stencil,
                precond=precond, storage_dtype=st,
            )
        # one compiled advance per adapter, the bound traced (no
        # recompile per chunk); carry not donated — the guard keeps the
        # previous healthy carry alive as the rollback point
        self.advance = jax.jit(self.advance_fn)  # tpulint: disable=TPU006

        h1 = jnp.asarray(problem.h1, dtype)
        h2 = jnp.asarray(problem.h2, dtype)
        d = diag_d(a, b, h1, h2)

        def recover(state):
            # true residual restart KEEPING the search direction (the
            # residual-replacement form — see module docstring); the
            # rebuilt z goes through the SAME preconditioner, so the
            # restarted recurrence still describes M⁻¹A. A narrow-
            # storage carry is upcast for the rebuild (ground truth is
            # computed at full width) and re-rounded on store.
            from poisson_ellipse_tpu.ops.precision import (
                load as _pld,
                store as _pst,
            )

            k, w_s, _r, p_s, _zr, diff, _c, _bd = state[:8]
            w = _pld(w_s, dtype, st)
            p = _pld(p_s, dtype, st)
            r2 = rhs - apply_a(w, a, b, h1, h2)
            z2 = apply_dinv(r2, d) if precond is None else precond(r2)
            zr2 = grid_dot(z2, r2, h1, h2)
            p2 = jnp.where(jnp.all(jnp.isfinite(p)), p, z2)
            return (
                k, w_s, _pst(r2, st), _pst(p2, st), zr2, diff,
                jnp.asarray(False), jnp.asarray(False),
            )

        self.recover = jax.jit(recover)  # tpulint: disable=TPU006

        def health(state, zr_prev, diff_prev, limit):
            k, w, r, p, zr, diff, conv, bd = state[:8]
            return _health_word(
                (w, r, p), zr, diff, k, conv, bd, zr_prev, diff_prev, limit
            )

        # no donation: the carry doubles as the guard's rollback point
        self.health = jax.jit(health)  # tpulint: disable=TPU004,TPU006

    def init(self):
        return self._init()

    def scalars(self, state):
        return state[self.ZR], state[self.DIFF]

    def result(self, state) -> PCGResult:
        from poisson_ellipse_tpu.solver.pcg import result_of

        return result_of(state)

    def promote(self):
        """Storage promotion — the bf16→f32 rung of the escalation
        ladder and the mandatory finishing step of every narrow solve:
        the ITERATE hands over to the full-width classical loop, the
        DIRECTION restarts from the rebuilt z. Keeping the narrow
        direction is not an option: it carries only storage-mantissa
        digits, and feeding it to the full-width α = zr/(Ap,p) breaks
        conjugacy and diverges (measured — the same lesson as the
        pipelined→classical phase correction). The NaN'd p slot routes
        recover() into its p = z branch."""
        if self.storage_dtype is None:
            return None
        adapter = _ClassicalAdapter(
            self.problem, self.dtype, stencil="xla",
            operands=self._operands, geometry=self.geometry,
            theta=self.theta,
        )
        dtype = self.dtype

        def convert(state):
            x = state[1].astype(dtype)
            return (
                state[0], x, jnp.zeros_like(x),
                jnp.full_like(x, jnp.nan),
                jnp.asarray(1.0, dtype), state[5].astype(dtype),
                jnp.asarray(False), jnp.asarray(False),
            )

        return adapter, convert

    def escalate(self):
        if self.storage_dtype is not None:
            # the rung BELOW f64: back to compute width first —
            # breakdown/stagnation under narrow storage is almost always
            # the storage floor, not an f32 phenomenon
            return self.promote()
        if self.precond_kind is not None:
            # the preconditioner engines walk their own ladder
            # (mg → cheb → diag, see fallback) before any dtype change
            return None
        if self.sstep_s is not None:
            # the s-step ladder is fallback-first (sstep → pipelined →
            # classical); precision escalation belongs to the floor rung
            return None
        if self.stencil != "xla" or jnp.dtype(self.dtype).itemsize >= 8:
            return None
        if not jax.config.jax_enable_x64:
            return None
        adapter = _ClassicalAdapter(
            # tpulint: disable=TPU001 — escalation is gated on x64 above
            self.problem, jnp.float64, stencil="xla",
            geometry=self.geometry, theta=self.theta,
        )
        # tpulint: disable=TPU001 — escalation is refused without x64
        return adapter, lambda state: _cast_carry(state, jnp.float64)

    def fallback(self):
        if self.sstep_s is not None:
            # sstep → pipelined: the carry hands over through a ground-
            # truth rebuild (classical layout in, pipelined layout out —
            # x and the direction p carry across; the pipelined
            # adapter's own fallback continues the ladder to classical)
            adapter = _PipelinedAdapter(
                self.problem, self.dtype, stencil="xla",
                geometry=self.geometry, theta=self.theta,
            )
            a, b, rhs = self._operands
            h1 = jnp.asarray(self.problem.h1, self.dtype)
            h2 = jnp.asarray(self.problem.h2, self.dtype)
            d = diag_d(a, b, h1, h2)
            dtype, st = self.dtype, self.storage_dtype

            def to_pipelined(state):
                from poisson_ellipse_tpu.ops.precision import load as _pld

                k, zr, diff = state[0], state[4], state[5]
                x = _pld(state[1], dtype, st)
                p = _pld(state[3], dtype, st)
                r2 = rhs - apply_a(x, a, b, h1, h2)
                u2 = apply_dinv(r2, d)
                w2 = apply_a(u2, a, b, h1, h2)
                s2 = apply_a(p, a, b, h1, h2)
                z2 = apply_a(apply_dinv(s2, d), a, b, h1, h2)
                g2 = jnp.where(
                    jnp.isfinite(zr) & (zr > 0), zr,
                    jnp.asarray(1.0, zr.dtype),
                )
                return (
                    k, x, r2, u2, w2, z2, s2, p, g2, diff,
                    jnp.asarray(False), jnp.asarray(False),
                )

            return adapter, jax.jit(to_pipelined)
        if self.precond_kind == "mg":
            # the carry layout is shared, so the iterate/direction hand
            # straight over; recover() rebuilds z/zr under the new M.
            # The spectral interval is an operator property, not a
            # preconditioner one: reuse the resolved bounds instead of
            # re-running the Lanczos probe mid-recovery
            import dataclasses as _dc

            from poisson_ellipse_tpu.mg.engine import default_config

            cheb_cfg = _dc.replace(
                default_config(self.problem, "cheb"),
                lo=self._precond_cfg.lo, hi=self._precond_cfg.hi,
            )
            adapter = _ClassicalAdapter(
                self.problem, self.dtype, stencil="xla",
                operands=self._operands, precond_kind="cheb",
                precond_config=cheb_cfg, geometry=self.geometry,
                theta=self.theta,
            )
            return adapter, lambda state: state
        if self.precond_kind == "cheb":
            adapter = _ClassicalAdapter(
                self.problem, self.dtype, stencil="xla",
                operands=self._operands, geometry=self.geometry,
                theta=self.theta,
            )
            return adapter, lambda state: state
        if self.stencil == "pallas":
            adapter = _ClassicalAdapter(
                self.problem, self.dtype, stencil="xla",
                operands=self._operands, geometry=self.geometry,
                theta=self.theta,
            )
            return adapter, lambda state: state
        return None


class _PipelinedAdapter:
    """The pipelined carry (``ops.pipelined_pcg``): (k, x, r, u, w, z, s,
    p, γ₋₁, diff, converged, breakdown). Its zr-series is γ."""

    FIELDS = {
        "x": 1, "r": 2, "u": 3, "w": 4, "z": 5, "s": 6, "p": 7, "gamma": 8,
    }
    K, ZR, DIFF, CONV, BD = 0, 8, 9, 10, 11

    def __init__(self, problem: Problem, dtype, stencil: str = "xla",
                 interpret=None, geometry=None, theta=None,
                 storage_dtype=None):
        from poisson_ellipse_tpu.ops import pipelined_pcg as _pp
        from poisson_ellipse_tpu.ops.precision import resolve_storage_dtype

        self.problem = problem
        self.dtype = dtype
        self.stencil = stencil
        self.interpret = interpret
        self.geometry = geometry
        self.theta = theta
        self.storage_dtype = resolve_storage_dtype(storage_dtype, dtype)
        st = self.storage_dtype
        self.engine = "pipelined" if stencil == "xla" else "pipelined-pallas"
        a, b, rhs = assembly.assemble(problem, dtype, geometry=geometry,
                                      theta=theta)
        self._operands = (a, b, rhs)
        self.rhs_norm = float(jnp.sqrt(jnp.sum(rhs.astype(jnp.float32) ** 2)))
        self._init = lambda: _pp.init_state(
            problem, a, b, rhs, stencil=stencil, interpret=interpret,
            storage_dtype=st,
        )
        self.advance_fn = lambda state, limit: _pp.advance(
            problem, a, b, rhs, state, limit=limit, stencil=stencil,
            interpret=interpret, storage_dtype=st,
        )
        self.advance = jax.jit(self.advance_fn)  # tpulint: disable=TPU006

        h1 = jnp.asarray(problem.h1, dtype)
        h2 = jnp.asarray(problem.h2, dtype)
        d = diag_d(a, b, h1, h2)

        def recover(state):
            # the in-loop residual replacement's rebuild, applied on
            # demand: every recurrence-maintained vector from ground
            # truth, direction p kept (ops.pipelined_pcg.replace); a
            # narrow-storage carry rebuilds at full width, re-rounded
            # on store
            from poisson_ellipse_tpu.ops.precision import (
                load as _pld,
                store as _pst,
            )

            k, x_s, _r, _u, _w, _z, _s_, p_s, g, diff, _c, _bd = state[:12]
            x = _pld(x_s, dtype, st)
            p = _pld(p_s, dtype, st)
            r2 = rhs - apply_a(x, a, b, h1, h2)
            u2 = apply_dinv(r2, d)
            w2 = apply_a(u2, a, b, h1, h2)
            s2 = apply_a(p, a, b, h1, h2)
            z2 = apply_a(apply_dinv(s2, d), a, b, h1, h2)
            g2 = jnp.where(jnp.isfinite(g), g, jnp.asarray(1.0, g.dtype))
            return (
                k, x_s, _pst(r2, st), _pst(u2, st), _pst(w2, st),
                _pst(z2, st), _pst(s2, st), p_s, g2, diff,
                jnp.asarray(False), jnp.asarray(False),
            )

        self.recover = jax.jit(recover)  # tpulint: disable=TPU006

        def health(state, zr_prev, diff_prev, limit):
            k = state[0]
            vectors = state[1:8]
            g, diff, conv, bd = state[8], state[9], state[10], state[11]
            return _health_word(
                vectors, g, diff, k, conv, bd, zr_prev, diff_prev, limit
            )

        # no donation: the carry doubles as the guard's rollback point
        self.health = jax.jit(health)  # tpulint: disable=TPU004,TPU006

        def to_classical(state):
            # The classical carry holds the direction for the NEXT
            # iteration (p_out = z + βp, built end-of-body); the
            # pipelined carry holds the direction its last iteration
            # USED (x⁺ = x + αp⁺ with p⁺ built in-body). Handing the
            # stale direction to the classical α = zr/(Ap,p) breaks the
            # (r, p) = (z, r) invariant and diverges (measured) — so the
            # conversion applies the classical end-of-iteration direction
            # update once: p₀ = z + (zr/γ)·p. A narrow-storage carry is
            # upcast here: the fault-path fallback always lands at full
            # width (conservative — correctness before bandwidth).
            from poisson_ellipse_tpu.ops.precision import load as _pld

            k = state[0]
            x = _pld(state[1], dtype, st)
            p = _pld(state[7], dtype, st)
            g, diff = state[8], state[9]
            r2 = rhs - apply_a(x, a, b, h1, h2)
            z2 = apply_dinv(r2, d)
            zr2 = grid_dot(z2, r2, h1, h2)
            p2 = z2 + (zr2 / g) * p
            return (
                k, x, r2, p2, zr2, diff,
                jnp.asarray(False), jnp.asarray(False),
            )

        self._to_classical = jax.jit(to_classical)  # tpulint: disable=TPU006

    def init(self):
        return self._init()

    def scalars(self, state):
        return state[self.ZR], state[self.DIFF]

    def result(self, state) -> PCGResult:
        from poisson_ellipse_tpu.ops.pipelined_pcg import result_of

        return result_of(state)

    def promote(self):
        """Storage promotion: iterate hands over to the full-width
        classical loop, direction restarts from z (see the classical
        adapter's promote — the narrow direction must not survive the
        precision boundary)."""
        if self.storage_dtype is None:
            return None
        adapter = _ClassicalAdapter(
            self.problem, self.dtype, stencil="xla",
            operands=self._operands, geometry=self.geometry,
            theta=self.theta,
        )
        dtype = self.dtype

        def convert(state):
            x = state[1].astype(dtype)  # the pipelined carry's iterate
            return (
                state[0], x, jnp.zeros_like(x),
                jnp.full_like(x, jnp.nan),
                jnp.asarray(1.0, dtype), state[9].astype(dtype),
                jnp.asarray(False), jnp.asarray(False),
            )

        return adapter, convert

    def escalate(self):
        if self.storage_dtype is not None:
            # back to compute width before any f64 talk (the bf16→f32
            # rung; stagnation under narrow storage is the storage floor)
            return self.promote()
        if self.stencil != "xla" or jnp.dtype(self.dtype).itemsize >= 8:
            return None
        if not jax.config.jax_enable_x64:
            return None
        adapter = _PipelinedAdapter(
            # tpulint: disable=TPU001 — escalation is gated on x64 above
            self.problem, jnp.float64, stencil="xla",
            geometry=self.geometry, theta=self.theta,
        )
        # tpulint: disable=TPU001 — escalation is refused without x64
        return adapter, lambda state: _cast_carry(state, jnp.float64)

    def fallback(self):
        # pipelined -> classical: the iterate and the (phase-corrected)
        # search direction carry over — see to_classical above. The
        # operands are shared: both recurrences consume the same
        # rounded-once (a, b, rhs), so no reassembly on the fault path.
        adapter = _ClassicalAdapter(
            self.problem, self.dtype, stencil="xla",
            operands=self._operands, geometry=self.geometry,
            theta=self.theta,
        )
        return adapter, self._to_classical


class _ShardedAdapter:
    """The mesh-sharded classical carry (``parallel.pcg_sharded``'s
    stepper): same layout as the single-chip classical carry, w/r/p
    global padded arrays sharded P('x','y'), scalars replicated.

    ``abft=True`` runs the stepper's in-loop SDC checks
    (``resilience.abft``) — the carry gains the four shadow scalars and
    the chunk-boundary health word gains the ``HEALTH_SDC`` bit, read
    through the same single host int. ``precond_kind`` ("mg"/"cheb")
    swaps in the mesh V-cycle/Chebyshev stepper
    (``parallel.mg_sharded.build_mg_sharded_stepper``) — chunk/health/
    recover machinery unchanged, recover rebuilds z/zr under the same M.
    """

    FIELDS = {"w": 1, "r": 2, "p": 3, "zr": 4}
    K, ZR, DIFF, CONV, BD = 0, 4, 5, 6, 7
    SDC = ABFT_SDC  # the abft-module-owned shadow-tail layout

    def __init__(self, problem: Problem, mesh, dtype, stencil: str = "xla",
                 abft: bool = False, precond_kind=None, sstep_s=None):
        from poisson_ellipse_tpu.parallel.pcg_sharded import (
            build_sharded_recover,
            build_sharded_stepper,
        )

        self.problem = problem
        self.mesh = mesh
        self.dtype = dtype
        self.stencil = stencil
        self.abft = abft
        self.precond_kind = precond_kind
        self.sstep_s = sstep_s
        self.storage_dtype = None  # the mesh ladder runs at full width
        if precond_kind is not None:
            from poisson_ellipse_tpu.parallel.mg_sharded import (
                build_mg_sharded_stepper,
            )
            from poisson_ellipse_tpu.solver.engine import (
                PRECOND_ENGINE_BY_KIND,
            )

            self.engine = PRECOND_ENGINE_BY_KIND[precond_kind]
            self._init, self.advance, self.recover = (
                build_mg_sharded_stepper(
                    problem, mesh, dtype, kind=precond_kind, abft=abft
                )
            )
        elif stencil == "sstep":
            # the s-step stepper shares the classical carry layout, so
            # the CLASSICAL recover applies verbatim (rebuild r/z/zr,
            # keep p, re-anchor the abft tail) — the whole point of
            # pinning the layouts together
            from poisson_ellipse_tpu.parallel.sstep_sharded import (
                build_sstep_sharded_stepper,
            )

            self.engine = "sstep"
            self._init, self.advance = build_sstep_sharded_stepper(
                problem, mesh, dtype, s=sstep_s or 4, abft=abft
            )
            self.recover = build_sharded_recover(
                problem, mesh, dtype, stencil_impl="xla", abft=abft
            )
        else:
            self.engine = stencil
            self._init, self.advance = build_sharded_stepper(
                problem, mesh, dtype, stencil_impl=stencil, abft=abft
            )
            self.recover = build_sharded_recover(
                problem, mesh, dtype, stencil_impl=stencil, abft=abft
            )
        self.advance_fn = self.advance  # already jit-wrapped by the stepper
        import numpy as np

        self.rhs_norm = float(
            np.linalg.norm(assembly.assemble_numpy(problem)[2])
        )

        def health(state, zr_prev, diff_prev, limit):
            k, w, r, p, zr, diff, conv, bd = state[:8]
            word = _health_word(
                (w, r, p), zr, diff, k, conv, bd, zr_prev, diff_prev, limit
            )
            if abft:
                word = word + state[self.SDC].astype(jnp.int32) * HEALTH_SDC
            return word

        # no donation: the carry doubles as the guard's rollback point
        self.health = jax.jit(health)  # tpulint: disable=TPU004,TPU006

    def init(self):
        return self._init()

    def scalars(self, state):
        return state[self.ZR], state[self.DIFF]

    def result(self, state) -> PCGResult:
        from poisson_ellipse_tpu.parallel.pcg_sharded import sharded_result_of

        return sharded_result_of(self.problem, state)

    def escalate(self):
        if self.precond_kind is not None:
            return None  # the preconditioner engines fall back first
        if self.stencil != "xla" or jnp.dtype(self.dtype).itemsize >= 8:
            return None
        if not jax.config.jax_enable_x64:
            return None
        adapter = _ShardedAdapter(
            # tpulint: disable=TPU001 — escalation is gated on x64 above
            self.problem, self.mesh, jnp.float64, stencil="xla",
            abft=self.abft,
        )
        # tpulint: disable=TPU001 — escalation is refused without x64
        return adapter, lambda state: _cast_carry(state, jnp.float64)

    def fallback(self):
        if self.precond_kind is not None:
            # mg/cheb mesh carries pad to their own level geometry —
            # hand over to the diagonal classical stepper through a
            # host crop/re-pad (parallel.elastic); the abft tail is
            # re-anchored by the recover that always follows a convert
            from poisson_ellipse_tpu.parallel.elastic import reshard_state

            adapter = _ShardedAdapter(
                self.problem, self.mesh, self.dtype, stencil="xla",
                abft=self.abft,
            )

            def convert(state):
                carry = reshard_state(
                    self.problem, state[:8], self.mesh, self.dtype
                )
                if self.abft:
                    carry = carry + _abft_dummy_tail(self.dtype)
                return carry

            return adapter, convert
        if self.stencil in ("pallas", "sstep"):
            # same carry layout: the iterate/direction hand straight
            # over (sstep → the classical 2-psum stepper; pallas → xla)
            adapter = _ShardedAdapter(
                self.problem, self.mesh, self.dtype, stencil="xla",
                abft=self.abft,
            )
            return adapter, lambda state: state
        return None


class _PipelinedShardedAdapter:
    """The pipelined mesh carry (``parallel.pipelined_sharded``'s
    stepper): x/r/u/w/z/s/p global padded arrays sharded P('x','y'),
    γ/diff/flags replicated, plus the lagged ABFT tail when ``abft``.
    Recovery math runs on the global arrays under plain jit (GSPMD
    partitions it) — off the hot path by construction."""

    FIELDS = {
        "x": 1, "r": 2, "u": 3, "w": 4, "z": 5, "s": 6, "p": 7,
    }
    K, ZR, DIFF, CONV, BD = 0, 8, 9, 10, 11
    SDC = 16  # = pipelined_sharded.PIPE_SDC, asserted at __init__

    def __init__(self, problem: Problem, mesh, dtype, abft: bool = False):
        import numpy as np

        from poisson_ellipse_tpu.parallel.mesh import padded_dims
        from poisson_ellipse_tpu.parallel.pipelined_sharded import (
            PIPE_SDC,
            build_pipelined_sharded_stepper,
        )

        assert self.SDC == PIPE_SDC  # the recurrence owns its tail layout

        self.problem = problem
        self.mesh = mesh
        self.dtype = dtype
        self.stencil = "xla"
        self.abft = abft
        self.storage_dtype = None  # the mesh ladder runs at full width
        self.engine = "pipelined"
        self._init, self.advance = build_pipelined_sharded_stepper(
            problem, mesh, dtype, abft=abft
        )
        self.advance_fn = self.advance
        a_np, b_np, rhs_np = assembly.assemble_numpy(problem)
        self.rhs_norm = float(np.linalg.norm(rhs_np))
        g1p, g2p = padded_dims(problem.node_shape, mesh)

        def pad(arr):
            return jnp.asarray(np.pad(
                arr, ((0, g1p - arr.shape[0]), (0, g2p - arr.shape[1]))
            ).astype(assembly.numpy_dtype(dtype)))

        a, b, rhs = pad(a_np), pad(b_np), pad(rhs_np)
        h1 = jnp.asarray(problem.h1, dtype)
        h2 = jnp.asarray(problem.h2, dtype)
        gi = jnp.arange(g1p, dtype=jnp.int32)
        gj = jnp.arange(g2p, dtype=jnp.int32)
        interior = assembly.interior_mask(problem, gi, gj)
        mask = interior.astype(dtype)
        d = jnp.where(interior, diag_d(a, b, h1, h2), 0.0)

        def recover(state):
            # the in-loop residual replacement's rebuild on the global
            # padded arrays (the interior mask reproduces the sharded
            # stencil's masking): every recurrence-maintained vector
            # from ground truth, direction p kept
            k, x = state[0], state[1]
            p, g, diff = state[7], state[8], state[9]
            r2 = (rhs - apply_a(x, a, b, h1, h2)) * mask
            u2 = apply_dinv(r2, d)
            w2 = apply_a(u2, a, b, h1, h2) * mask
            s2 = apply_a(p, a, b, h1, h2) * mask
            z2 = apply_a(apply_dinv(s2, d), a, b, h1, h2) * mask
            g2 = jnp.where(
                jnp.isfinite(g) & (g > 0), g, jnp.asarray(1.0, g.dtype)
            )
            out = (
                k, x, r2, u2, w2, z2, s2, p, g2, diff,
                jnp.asarray(False), jnp.asarray(False),
            )
            if abft:
                # re-anchor the lagged checks to the rebuilt residual
                # and the kept direction
                out = out + (
                    jnp.sum(r2), jnp.sum(jnp.abs(r2)),
                    jnp.sum(p), jnp.sum(jnp.abs(p)),
                    jnp.asarray(False),
                )
            return out

        self.recover = jax.jit(recover)  # tpulint: disable=TPU006

        def health(state, zr_prev, diff_prev, limit):
            word = _health_word(
                state[1:8], state[8], state[9], state[0], state[10],
                state[11], zr_prev, diff_prev, limit
            )
            if abft:
                word = word + state[self.SDC].astype(jnp.int32) * HEALTH_SDC
            return word

        # no donation: the carry doubles as the guard's rollback point
        self.health = jax.jit(health)  # tpulint: disable=TPU004,TPU006

        def to_classical(state):
            # same direction phase correction as the single-chip
            # pipelined→classical conversion (see _PipelinedAdapter)
            k, x = state[0], state[1]
            p, g, diff = state[7], state[8], state[9]
            r2 = (rhs - apply_a(x, a, b, h1, h2)) * mask
            z2 = apply_dinv(r2, d)
            zr2 = grid_dot(z2, r2, h1, h2)
            p2 = z2 + (zr2 / g) * p
            out = (
                k, x, r2, p2, zr2, diff,
                jnp.asarray(False), jnp.asarray(False),
            )
            if abft:
                out = out + _abft_dummy_tail(dtype)
            return out

        self._to_classical = jax.jit(to_classical)  # tpulint: disable=TPU006

    def init(self):
        return self._init()

    def scalars(self, state):
        return state[self.ZR], state[self.DIFF]

    def result(self, state) -> PCGResult:
        from poisson_ellipse_tpu.parallel.pipelined_sharded import (
            pipelined_sharded_result_of,
        )

        return pipelined_sharded_result_of(self.problem, state)

    def escalate(self):
        return None  # the mesh ladder is restart → classical fallback

    def fallback(self):
        adapter = _ShardedAdapter(
            self.problem, self.mesh, self.dtype, stencil="xla",
            abft=self.abft,
        )
        return adapter, self._to_classical


def _make_adapter(problem: Problem, engine: str, dtype, mesh, interpret,
                  abft: bool = False, geometry=None, theta=None,
                  storage_dtype=None, sstep_s: int = 4):
    if geometry is not None and mesh is not None:
        raise ValueError(
            "guarded sharded solves do not take geometry= yet — run the "
            "sharded build (parallel.pcg_sharded.build_sharded_solver) "
            "directly, or guard the single-chip engines"
        )
    if abft and mesh is None:
        raise ValueError(
            "abft covers the sharded engines (the checksum partials ride "
            "the mesh's stacked convergence psum); single-device solves "
            "are guarded by the health word + final residual gate alone"
        )
    if mesh is not None:
        if storage_dtype is not None:
            raise ValueError(
                "the guarded mesh ladder runs at full width; narrow-"
                "storage sharded solves run the steppers directly "
                "(parallel.pcg_sharded / parallel.sstep_sharded with "
                "storage_dtype=) — drop --storage-dtype or --mesh"
            )
        if engine in ("auto", "xla"):
            return _ShardedAdapter(problem, mesh, dtype, stencil="xla",
                                   abft=abft)
        if engine == "pallas":
            return _ShardedAdapter(problem, mesh, dtype, stencil="pallas",
                                   abft=abft)
        if engine in ("sstep", "sstep-pallas"):
            return _ShardedAdapter(problem, mesh, dtype, stencil="sstep",
                                   abft=abft, sstep_s=sstep_s)
        if engine in ("mg-pcg", "cheb-pcg"):
            from poisson_ellipse_tpu.solver.engine import (
                PRECOND_KIND_BY_ENGINE,
            )

            return _ShardedAdapter(
                problem, mesh, dtype, stencil="xla", abft=abft,
                precond_kind=PRECOND_KIND_BY_ENGINE[engine],
            )
        if engine == "pipelined":
            return _PipelinedShardedAdapter(problem, mesh, dtype, abft=abft)
        raise ValueError(
            f"guarded sharded solves run the chunked steppers "
            f"('xla'/'pallas'/'pipelined'/'sstep'/'mg-pcg'/'cheb-pcg'); "
            f"got engine={engine!r} — the fused sharded iteration has no "
            "resumable stepper form"
        )
    if engine == "xla":
        return _ClassicalAdapter(problem, dtype, stencil="xla",
                                 geometry=geometry, theta=theta,
                                 storage_dtype=storage_dtype)
    if engine in ("sstep", "sstep-pallas"):
        return _ClassicalAdapter(
            problem, dtype,
            stencil="xla" if engine == "sstep" else "pallas",
            interpret=interpret, geometry=geometry, theta=theta,
            storage_dtype=storage_dtype, sstep_s=sstep_s,
        )
    if engine in ("mg-pcg", "cheb-pcg", "fmg"):
        from poisson_ellipse_tpu.solver.engine import PRECOND_KIND_BY_ENGINE

        if storage_dtype is not None:
            # mirror build_solver's STORAGE_ENGINES stance: the mg/cheb
            # appliers carry their own full-width level hierarchies —
            # silently running full-width while the report says narrow
            # would corrupt every bandwidth comparison built on it
            raise ValueError(
                "the multigrid engines (mg-pcg/cheb-pcg/fmg) have no "
                "storage-dtype form; drop --storage-dtype or use a "
                "diagonal-preconditioned loop engine"
            )
        if engine == "fmg":
            # full multigrid under the guard: the F-cycle runs once as
            # an (unchunked, fixed-work) prelude, then the VERIFICATION
            # loop — warm-started mg-pcg — is what the guard chunks,
            # health-checks and recovers; its ladder is the V-cycle's
            # (mg → cheb → diag), and every recovery keeps the iterate,
            # so the F-cycle's head start survives a NaN'd chunk
            from poisson_ellipse_tpu.mg.fmg import fmg_initial_guess

            x0, operands, _cfg = fmg_initial_guess(
                problem, dtype, geometry=geometry, theta=theta
            )
            # the F-cycle already resolved the hierarchy + Lanczos
            # interval; hand its config over so the verification
            # loop's preconditioner build skips the second probe
            adapter = _ClassicalAdapter(
                problem, dtype, stencil="xla", operands=operands,
                precond_kind="mg", precond_config=_cfg.precond_config(),
                geometry=geometry, theta=theta, x0=x0,
            )
            adapter.engine = "fmg"
            return adapter
        return _ClassicalAdapter(
            problem, dtype, stencil="xla",
            precond_kind=PRECOND_KIND_BY_ENGINE[engine],
            geometry=geometry, theta=theta,
        )
    if engine == "pallas":
        return _ClassicalAdapter(
            problem, dtype, stencil="pallas", interpret=interpret,
            geometry=geometry, theta=theta, storage_dtype=storage_dtype,
        )
    if engine == "pipelined":
        return _PipelinedAdapter(
            problem, dtype, stencil="xla", interpret=interpret,
            geometry=geometry, theta=theta, storage_dtype=storage_dtype,
        )
    if engine == "pipelined-pallas":
        return _PipelinedAdapter(
            problem, dtype, stencil="pallas", interpret=interpret,
            geometry=geometry, theta=theta, storage_dtype=storage_dtype,
        )
    if engine in ("batched", "batched-pipelined"):
        raise ValueError(
            f"engine {engine!r} has its own chunked guard — the lane "
            "driver (batch.driver.solve_batched) quarantines poisoned "
            "lanes per chunk instead of walking the single-solve ladder"
        )
    raise ValueError(f"no chunked adapter for engine {engine!r}")


# --------------------------------------------------------------------------
# the guard driver
# --------------------------------------------------------------------------


def guarded_solve(
    problem: Problem,
    engine: str = "xla",
    dtype=jnp.float32,
    *,
    mesh=None,
    chunk: int = DEFAULT_CHUNK,
    max_recoveries: int = 3,
    timeout: Optional[float] = None,
    faults: Optional[FaultPlan] = None,
    interpret=None,
    abft: bool = False,
    geometry=None,
    theta=None,
    validate_geometry: bool = True,
    storage_dtype=None,
    sstep_s: int = 4,
) -> GuardedResult:
    """Solve with failure detection and the recovery ladder (module
    docstring). Loop engines (xla / pallas / pipelined / pipelined-pallas
    / mg-pcg / cheb-pcg, and the sharded classical stepper via ``mesh=``)
    run chunked with a per-chunk health word; the VMEM mega-kernel
    engines (resident / streamed / xl / fused, and ``auto``) run
    whole-solve with the capacity-ladder fallback. The preconditioner
    engines walk their own fallback ladder — mg-pcg → cheb-pcg → the
    diagonal classical loop — after the residual restart.

    ``timeout`` (seconds) is enforced at chunk boundaries — the cancel
    is graceful: the in-flight chunk completes, then
    :class:`SolveTimeout` carries the last healthy iteration count out.
    ``faults`` is the deterministic injection plan (tests, ``harness
    inject``); production callers pass none.

    ``abft=True`` (sharded engines only) turns on the in-loop
    silent-corruption checks of ``resilience.abft``: a flagged chunk is
    classified apart from breakdown and recovered by rolling back to
    the last healthy chunk boundary and RE-RUNNING — never a
    residual-replacement restart, which would launder the corruption
    into the iterate. Corruption that re-fires from a clean carry
    raises the classified :class:`SilentCorruptionError` (exit 6).

    Raises the classified :class:`SolveError` subclasses on recovery
    exhaustion (``DivergedError``), memory exhaustion with no engine
    left (``OutOfMemoryError``), or deadline (``SolveTimeout``). A
    non-finite carry is never returned as a converged result.

    ``storage_dtype`` ("bf16"/"f16") runs the bandwidth-saving narrow-
    storage loop (``ops.precision``) UNDER the guard — the product path
    for mixed precision: the escalation ladder grows the bf16→f32 rung
    (storage *promotion*), and every narrow solve is promoted to full
    compute width before the guard will accept its convergence, so the
    returned result meets the same final true-residual gate as a full-
    width run. ``sstep_s`` sizes the s-step engines' blocks.
    """
    if chunk < 1:
        raise ValueError("chunk must be >= 1")
    t0 = time.monotonic()
    plan = faults if faults is not None else FaultPlan()
    events: list[RecoveryEvent] = []

    if geometry is not None:
        from poisson_ellipse_tpu.geom import sdf as geom_sdf
        from poisson_ellipse_tpu.geom import validate as geom_validate

        if isinstance(geometry, dict):
            geometry = geom_sdf.from_spec(geometry)
        if validate_geometry:
            # the admissibility gate runs before ANY device loop — a bad
            # problem is a classified exit-8 rejection, never a recovery
            # ladder walk (``validate_geometry=False`` is the fuzz
            # harness's bypass drill)
            geom_validate.validate(problem, geometry, theta=theta)

    if mesh is None and engine in ("auto", "resident", "streamed", "xl",
                                   "fused"):
        if abft:
            raise ValueError(
                "abft covers the sharded engines; the whole-solve VMEM "
                f"engines ({engine!r}) are validated by the final "
                "health check alone"
            )
        if storage_dtype is not None:
            raise ValueError(
                "guarded narrow-storage solves run the chunked loop "
                "engines (xla/pallas/pipelined*/sstep*) — the whole-"
                "solve VMEM engines have no chunk boundary to promote "
                "at; run build_solver(storage_dtype=…) directly for "
                "their operand-narrow forms"
            )
        return _guarded_whole_solve(
            problem, engine, dtype, interpret=interpret, chunk=chunk,
            max_recoveries=max_recoveries, timeout=timeout, t0=t0,
            plan=plan, events=events, geometry=geometry, theta=theta,
        )

    adapter = _make_adapter(problem, engine, dtype, mesh, interpret,
                            abft=abft, geometry=geometry, theta=theta,
                            storage_dtype=storage_dtype, sstep_s=sstep_s)
    return _run_chunked(
        problem, adapter, chunk=chunk, max_recoveries=max_recoveries,
        timeout=timeout, t0=t0, plan=plan, events=events,
    )


def _record(events: list[RecoveryEvent], kind: str, at_iter: int, health: int,
            engine: str, detail: str = "", lane: int | None = None) -> None:
    events.append(RecoveryEvent(kind, at_iter, health, engine, detail))
    obs_trace.event(
        f"recovery:{kind}",
        # lane-addressed events (the batched driver's quarantines) carry
        # the lane as the schema's first-class top-level key, not a
        # fields poke — obs.trace.validate_record checks it
        lane=lane,
        iter=at_iter,
        health=health_name(health) if health else "error",
        engine=engine,
        detail=detail,
    )


def _residual_drift(adapter, state) -> float:
    """Relative drift of the carried residual vs ground truth — the
    convergence-claim check. Reuses the adapter's recover dispatch (its
    rebuilt r IS the true residual); one extra dispatch, final chunk
    only."""
    rebuilt = adapter.recover(state)
    idx = adapter.FIELDS["r"]
    num = jnp.sqrt(jnp.sum((state[idx] - rebuilt[idx]).astype(jnp.float32) ** 2))
    return float(num) / max(adapter.rhs_norm, 1e-30)


def _check_deadline(timeout, t0, k: int) -> None:
    if timeout is not None and time.monotonic() - t0 > timeout:
        obs_trace.event("recovery:timeout", iter=k, timeout_s=timeout)
        raise SolveTimeout(
            f"solve exceeded --timeout {timeout:g}s at iteration {k} "
            "(chunk-boundary cancel; partial trace flushed)",
            iters=k,
        )


def _run_chunked(problem, adapter, *, chunk, max_recoveries, timeout, t0,
                 plan, events) -> GuardedResult:
    state = adapter.init()
    prev = state  # last healthy chunk-boundary carry: the rollback point
    k = 0
    nrec = 0
    consecutive = 0
    stag_strikes = 0
    sdc_strikes = 0
    max_iter = problem.max_iterations

    while True:
        _check_deadline(timeout, t0, k)
        stop = plan.next_stop(k - 1)  # a fault AT k fires before this chunk
        limit = min(k + chunk, max_iter)
        if stop is not None and k < stop:
            limit = min(limit, stop)
        try:
            run_state = plan.apply(
                k, state, adapter.FIELDS, adapter.BD, adapter.ZR
            ) if plan else state
            new = adapter.advance(run_state, limit)
            word = int(adapter.health(new, *adapter.scalars(state), limit))
        except SolveError:
            raise
        except Exception as e:  # noqa: BLE001 — classified below, re-raised
            if classify_error(e) != "oom":
                raise  # unknown failures stay loud, never retried
            nrec += 1
            if nrec > max_recoveries:
                raise OutOfMemoryError(
                    f"OOM after {max_recoveries} recoveries: {e}", iters=k
                ) from e
            fb = adapter.fallback()
            if fb is None:
                raise OutOfMemoryError(
                    f"{adapter.engine} hit RESOURCE_EXHAUSTED with no "
                    f"smaller engine to degrade to: {e}",
                    iters=k,
                ) from e
            adapter2, convert = fb
            _record(
                events, "engine-fallback", k, 0, adapter2.engine,
                detail=f"oom on {adapter.engine}: {e}",
            )
            state = prev = adapter2.recover(convert(prev))
            adapter = adapter2
            consecutive = 1
            stag_strikes = 0
            continue

        if word & HEALTH_SDC and not word & HEALTH_NONFINITE:
            # Silent corruption, classified apart from breakdown: the
            # recovery is rollback-to-last-healthy-boundary + RE-RUN —
            # never residual replacement, which would rebuild the
            # recurrence around the corrupted iterate and launder the
            # corruption into the answer. A transient flip re-runs
            # clean (and to oracle parity — the rollback point is
            # bit-exact); one that re-fires from a clean carry is a
            # persistent SDC source and must surface, loudly.
            nrec += 1
            if sdc_strikes >= 1 or nrec > max_recoveries:
                raise SilentCorruptionError(
                    "silent data corruption re-detected after a clean "
                    f"rollback-and-rerun at iteration ~{int(prev[adapter.K])}"
                    " — persistent corruption source; refusing to return "
                    "an iterate it may have touched",
                    iters=int(prev[adapter.K]),
                )
            _record(
                events, "sdc-rollback", int(prev[adapter.K]), word,
                adapter.engine,
                detail="ABFT checksum/invariant violation; rolling back "
                "to the last healthy chunk boundary and re-running",
            )
            state = prev
            k = int(prev[adapter.K])
            sdc_strikes += 1
            stag_strikes = 0
            continue

        storage = getattr(adapter, "storage_dtype", None)
        if storage is not None and not word & _UNHEALTHY:
            # A narrow-storage solve never finishes narrow. Promote to
            # full compute width when (a) the narrow loop claims
            # convergence — the claim is re-earned at full width before
            # the drift gate ever sees it — or (b) a full chunk's
            # progress collapsed (step norm no longer halving): the
            # storage floor, where further narrow iterations are
            # quantisation noise. Promotion is the DESIGNED finish of
            # every narrow solve, not a failure: it does not spend the
            # recovery budget (and it is bounded — the promoted adapter
            # has no storage dtype to promote again).
            _zb, diff_before = adapter.scalars(state)
            _za, diff_after = adapter.scalars(new)
            db, da = float(diff_before), float(diff_after)
            full_chunk = (limit - k) >= chunk
            at_floor = (
                full_chunk and da == da and db == db
                and db != float("inf") and da >= 0.5 * db
            )
            if word & HEALTH_CONVERGED or at_floor:
                adapter2, convert = adapter.promote()
                _record(
                    events, "storage-promotion", int(new[adapter.K]), word,
                    adapter2.engine,
                    detail=f"{jnp.dtype(storage).name} storage -> "
                    f"{jnp.dtype(adapter.dtype).name} compute ("
                    + ("converged at storage width"
                       if word & HEALTH_CONVERGED else "storage floor")
                    + "); polishing at full width",
                )
                state = prev = adapter2.recover(convert(new))
                k = int(new[adapter.K])
                adapter = adapter2
                consecutive = 0
                stag_strikes = 0
                sdc_strikes = 0
                continue

        if word & HEALTH_CONVERGED and not word & _UNHEALTHY:
            drift = _residual_drift(adapter, new)
            if drift <= RESIDUAL_DRIFT_TOL:
                state = new
                break
            # the stopping rule fired on a drifted recurrence: the
            # iterate is NOT a solution — a silent wrong answer without
            # this check. Treat as stagnation and recover now (a false
            # convergence cannot resolve itself: the loop just exits
            # again, so the stagnation debounce below is pointless here).
            word = (word & ~HEALTH_CONVERGED) | HEALTH_STAGNATION
            stag_strikes = 1
        if not word & _UNHEALTHY:
            state = prev = new
            k = limit
            consecutive = 0
            stag_strikes = 0
            sdc_strikes = 0
            if k >= max_iter:
                break
            continue

        if (
            (word & _UNHEALTHY) == HEALTH_STAGNATION
            and stag_strikes == 0
            and limit < max_iter
        ):
            # Debounce pure stagnation one chunk: a recovery or engine
            # transition legitimately bumps zr/diff for a few iterations
            # while CG re-adapts its direction (measured on the
            # pipelined->classical fallback). prev stays PINNED at the
            # last trusted boundary — if the stall is real, the next
            # strike rolls back to it, so nothing is lost but one chunk
            # of wall clock. Breakdown/NaN stay immediate.
            stag_strikes = 1
            state = new
            k = limit
            continue

        # ---- unhealthy chunk: walk the recovery ladder -------------------
        nrec += 1
        if nrec > max_recoveries:
            raise DivergedError(
                f"recovery budget exhausted ({max_recoveries}): solve is "
                f"{health_name(word)} at iteration ~{k}",
                iters=k,
            )
        # breakdown discards its own update, so the carry it stops with
        # is trustworthy; NaN/stagnation poison the chunk — roll back
        base = new if (word & _UNHEALTHY) == HEALTH_BREAKDOWN else prev
        k = int(base[adapter.K])
        stag_strikes = 0

        if consecutive == 0:
            _record(events, "residual-restart", k, word, adapter.engine)
            state = prev = adapter.recover(base)
            consecutive = 1
            continue
        esc = adapter.escalate()
        if esc is not None:
            adapter2, convert = esc
            _record(
                events, "precision-escalation", k, word, adapter2.engine,
                detail=f"{jnp.dtype(adapter.dtype).name} -> "
                f"{jnp.dtype(adapter2.dtype).name}",
            )
            state = prev = adapter2.recover(convert(base))
            adapter = adapter2
            consecutive = 1
            continue
        fb = adapter.fallback()
        if fb is not None:
            adapter2, convert = fb
            _record(
                events, "engine-fallback", k, word, adapter2.engine,
                detail=f"from {adapter.engine}",
            )
            state = prev = adapter2.recover(convert(base))
            adapter = adapter2
            consecutive = 1
            continue
        raise DivergedError(
            f"recovery ladder exhausted: {adapter.engine} solve still "
            f"{health_name(word)} at iteration ~{k} after restart",
            iters=k,
        )

    result = adapter.result(state)
    return GuardedResult(
        result=result,
        recoveries=tuple(events),
        engine=adapter.engine,
        dtype=jnp.dtype(adapter.dtype).name,
    )


def _fire_whole_solve_oom(plan: FaultPlan) -> None:
    """Whole-solve engines have no iteration boundaries: any pending
    ``oom`` fault fires at the next engine attempt."""
    from poisson_ellipse_tpu.resilience.faultinject import (
        SimulatedResourceExhausted,
    )

    for fault in plan.faults:
        if not fault.fired and fault.kind == "oom":
            fault.fired = True
            raise SimulatedResourceExhausted(
                "RESOURCE_EXHAUSTED: simulated device OOM (fault "
                "injection, whole-solve attempt)"
            )


def _guarded_whole_solve(problem, engine, dtype, *, interpret, chunk,
                         max_recoveries, timeout, t0, plan,
                         events, geometry=None, theta=None) -> GuardedResult:
    """Guard for the VMEM mega-kernel engines: health-check the whole
    solve's result, degrade down the capacity ladder on OOM or an
    unhealthy result, and finish on the chunked guarded xla loop (which
    has no capacity gate and full ladder recovery)."""
    from poisson_ellipse_tpu.solver.engine import build_solver, select_engine

    if any(not f.fired and f.kind != "oom" for f in plan.faults):
        raise ValueError(
            "carry-field faults need a chunked engine (xla/pallas/"
            f"pipelined/pipelined-pallas); {engine!r} runs whole-solve "
            "and only supports 'oom' injection"
        )
    resolved = select_engine(problem, dtype) if engine == "auto" else engine
    if resolved in _CAPACITY_LADDER:
        chain = _CAPACITY_LADDER[_CAPACITY_LADDER.index(resolved):]
    else:
        chain = (resolved,)

    nrec = 0
    for cand in chain:
        _check_deadline(timeout, t0, 0)
        try:
            _fire_whole_solve_oom(plan)
            # one build per capacity rung is the whole-solve guard's
            # fallback, bounded by the ladder
            solver, args, _ = build_solver(
                # tpulint: disable=TPU013 — one build per capacity rung
                problem, cand, dtype, interpret, geometry=geometry,
                theta=theta, validate_geometry=False,
            )
            result = solver(*args)
            healthy = (
                bool(jnp.all(jnp.isfinite(result.w)))
                and not bool(result.breakdown)
            )
            if healthy:
                return GuardedResult(
                    result=result,
                    recoveries=tuple(events),
                    engine=cand,
                    dtype=jnp.dtype(dtype).name,
                )
            word = (
                HEALTH_BREAKDOWN if bool(result.breakdown)
                else HEALTH_NONFINITE
            )
            detail = f"unhealthy whole-solve result from {cand}"
        except SolveError:
            raise
        except Exception as e:  # noqa: BLE001 — classified below, re-raised
            if classify_error(e) != "oom":
                raise  # unknown failures stay loud
            word, detail = 0, f"oom on {cand}: {e}"
        nrec += 1
        if nrec > max_recoveries:
            raise OutOfMemoryError(
                f"recovery budget exhausted ({max_recoveries}) degrading "
                f"the capacity ladder at {cand}",
                iters=0,
            )
        # the event's engine field names the engine fallen back TO (the
        # chunked path's convention); the failed one rides in detail
        idx = chain.index(cand)
        target = chain[idx + 1] if idx + 1 < len(chain) else "xla"
        _record(events, "engine-fallback", 0, word, target, detail=detail)

    # the ladder's floor: the chunked guarded xla loop
    remaining_timeout = (
        None if timeout is None else max(timeout - (time.monotonic() - t0), 0.1)
    )
    adapter = _ClassicalAdapter(problem, dtype, stencil="xla",
                                geometry=geometry, theta=theta)
    return _run_chunked(
        problem, adapter, chunk=chunk,
        max_recoveries=max(max_recoveries - nrec, 0),
        timeout=remaining_timeout, t0=time.monotonic(), plan=plan,
        events=events,
    )
