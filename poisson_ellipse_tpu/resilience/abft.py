"""Algorithm-based fault tolerance: SDC detection inside the sharded loop.

Device loss is loud; *silent* data corruption is not — a flipped HBM
word or a corrupted halo exchange changes the iterate and nothing else,
and an iterative solver will happily converge its stopping rule on a
wrong answer (the drifted-recurrence false convergence the guard's
residual-drift check already exists for). The classical defence for
sparse iterative solves is algorithm-based: the CG iteration maintains
algebraic identities that corruption breaks and round-off does not
(Huang & Abraham's checksum line; Sao & Vuduc's self-stabilizing CG).
This module is that defence for the sharded engines, at ZERO extra
collective cost:

- **Stencil checksum (Huang–Abraham).** With ``c = A·1`` (the masked
  row-sum vector, one stencil application at build time, outside the
  loop), every iteration satisfies ``Σ(A·p) = Σ(c∘p)`` exactly — a
  corrupted halo exchange breaks it (the neighbour used a value the
  owner never sent), a flipped word in the stencil's output breaks it,
  and f.p. reordering only moves it at round-off scale.
- **Sum recurrences on the carry.** ``Σr`` obeys
  ``Σr⁺ = Σr − α·Σ(Ap)``, ``Σw`` obeys ``Σw⁺ = Σw + α·Σp``, and ``Σp``
  obeys ``Σp⁺ = Σz + β·Σp`` — one scalar shadow per vector, re-anchored
  to the directly-reduced sum every iteration, so a flip in any carried
  field between two iterations is caught at the next reduction.
- **⟨z, r⟩ positivity.** The preconditioned residual inner product is an
  energy norm — strictly positive for the SPD operator until
  convergence. A sign-flipped all-reduce result (``psum_corrupt``)
  violates it immediately.

Every partial these checks need rides THE existing stacked convergence
psum (``parallel.pcg_sharded._shard_advance`` stacks them into the same
``lax.psum`` the loop already issues), so the collective cadence stays
exactly what the engine advertises — 1 stacked psum + 1 denom psum per
classical iteration, 1 stacked psum per pipelined iteration — pinned
from the jaxpr via ``obs.static_cost`` in ``tests/test_elastic.py``.
The partial sums themselves are reductions over arrays the iteration
already reads or writes (``Ap``, ``r⁺``, ``w⁺``, ``p``, ``z``), fused by
XLA into the passes that produce them: no extra HBM traffic beyond the
one loop-invariant checksum field ``c`` (computed per dispatch, outside
the loop). The measured gate is the ``abft`` bench key: checks-on vs
checks-off healthy-path overhead ≤ 2% of T_solver with identical
collective counts.

Detection model (documented, not hoped): a corruption is flagged when
its magnitude is significant relative to the field's 1-norm
(``drift > rtol·scale``) — high-exponent/sign flips, NaN/Inf patterns,
wholesale slab corruption. Low-mantissa flips sit below the round-off
floor of a global f32 reduction and are *numerically absorbed*: CG
treats them as an ulp-scale perturbation, and the guard's final
true-residual gate (``RESIDUAL_DRIFT_TOL``) still validates whatever is
returned. ``rtol`` is dtype-scaled: pairwise XLA reductions accumulate
~eps·log₂(n) relative error, and the tolerance sits two-plus orders
above that floor.

Classification is the point: at a chunk boundary the guard reads the
accumulated on-device ``sdc`` flag through the same one-word health
read it already does, and routes SDC *differently* from breakdown —
**rollback to the last healthy chunk boundary and re-run**, never a
residual-replacement restart (which would rebuild the recurrence around
the corrupted iterate and launder the corruption into the answer). A
transient flip re-runs clean at oracle iteration parity; a corruption
that re-fires from a clean carry is persistent hardware and raises the
classified :class:`~poisson_ellipse_tpu.resilience.errors.
SilentCorruptionError` (exit 6) — never a silently wrong solution.
"""

from __future__ import annotations

import jax.numpy as jnp

# indices of the ABFT shadow scalars appended to the classical sharded
# carry: (…, S_r, S_w, S_p_pred, sdc). This module OWNS the tail layout
# — pcg_sharded's loop, the guard's sharded adapter and the meshguard
# all address it through these names (the pipelined carry's differently
# shaped tail lives with its recurrence: parallel.pipelined_sharded's
# PIPE_* constants).
SR, SW, SP_PRED, SDC = 8, 9, 10, 11
N_ABFT_SCALARS = 4


def abft_dummy_tail(dtype):
    """Placeholder shadow scalars for a converted/restored carry: every
    conversion is followed by a ``recover`` (or fresh anchor psum) that
    re-anchors them against the rebuilt arrays — shadow sums are never
    copied across a layout change."""
    return (
        jnp.asarray(0.0, dtype), jnp.asarray(0.0, dtype),
        jnp.asarray(0.0, dtype), jnp.asarray(False),
    )

# tolerance floor ~ eps·log2(n) for XLA's pairwise reductions, with two-plus
# orders of margin; keyed by itemsize so bf16 and f16 share a band
_RTOL_BY_ITEMSIZE = {2: 3e-2, 4: 1e-3, 8: 1e-8}

# guard floor for relative scales: |drift| <= rtol*(scale + ABFT_TINY)
# keeps an all-zero field (converged, padded) from dividing by nothing
ABFT_TINY = 1e-30


def abft_rtol(dtype) -> float:
    """The relative drift tolerance for checksum checks at ``dtype``."""
    return _RTOL_BY_ITEMSIZE[jnp.dtype(dtype).itemsize]


def checksum_field(stencil, interior_mask):
    """``c = A·1`` — the Huang–Abraham row-sum checksum field for one
    shard, via the engine's OWN masked stencil closure (so the identity
    ``Σ(A·p) = Σ(c∘p)`` holds for exactly the operator the loop runs,
    halo exchange included). One stencil application per *dispatch*,
    outside the iteration loop — never per iteration."""
    return stencil(interior_mask)
