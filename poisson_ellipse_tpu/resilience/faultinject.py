"""Deterministic fault injection: the recovery paths get *exercised*.

A recovery ladder nobody can trigger is dead code with a comforting
docstring. This module is the chaos harness the guarded solve
(:mod:`.guard`) is tested — and demoed (``harness inject``) — against:
every fault class the guard claims to survive can be injected at an
exact, reproducible point, with no randomness and no real hardware
failure required.

Fault classes (each maps to one detection bit or error path in the
guard):

- ``nan``        — poison a named carry field (default ``r``) with NaN at
                   iteration ``k``: the silent-f32-propagation failure.
- ``breakdown``  — raise the carry's breakdown flag at iteration ``k``:
                   the (Ap, p) < 1e-15 exit every engine detects but none
                   recovered from.
- ``stagnation`` — blow the carried ``zr`` (γ for the pipelined
                   recurrence) up to 1e30 at iteration ``k``: the next α
                   is garbage, the iterates jump far from the solution,
                   and the solve makes no further progress — the drifted-
                   recurrence failure mode of the pipelined literature.
- ``halo``       — overwrite a halo-width slab of a carry field with NaN:
                   the corrupted-neighbour-exchange shape of the same
                   poisoning, meaningful on sharded carries.
- ``oom``        — raise a ``RESOURCE_EXHAUSTED``-classified error from
                   the solve dispatch at iteration ``k``: what a real
                   device OOM looks like to the host.
- ``halo_bitflip`` — flip ONE bit of ONE element of a carry field at a
                   shard-boundary row: the canonical silent-data-
                   corruption (SDC) shape — a corrupted halo exchange or
                   a flipped HBM word that no NaN check can see. The
                   default bit is a high exponent bit (itemsize·8 − 5:
                   ×2¹²⁸ in f64), the corruption class that matters; low
                   mantissa flips are numerically absorbed by CG and
                   validated away by the guard's final true-residual
                   gate.
- ``psum_corrupt`` — flip the sign of the carried ⟨z, r⟩ scalar (bit 31
                   of the psum result, exactly): a corrupted all-reduce.
                   Detected by the ABFT positivity invariant — (z, r) is
                   an energy inner product, strictly positive until
                   convergence.
- ``device_loss`` — raise a ``DEVICE_LOST``-classified error from the
                   dispatch at chunk-boundary iteration ``k``: what a
                   dead mesh device looks like to the host. ``device``
                   names the lost device id for the degraded-mesh
                   rebuild (``resilience.meshguard``).
- ``straggler``  — sleep ``delay_s`` at the chunk boundary before the
                   dispatch: the slow-device shape. The mesh guard's
                   per-chunk deadline turns it into a detected
                   degradation, exactly like a loss.
- ``replica_kill`` / ``replica_hang`` / ``lease_clock_skew`` — the
                   REPLICA-level faults the fleet router consults
                   (``fleet.router``): SIGKILL a whole scheduler
                   replica at arrival ``at_request``, hang its
                   heartbeat while the process lives (the zombie
                   drill), or skew its lease clock (the NTP-step
                   drill). All seed-deterministic and addressable from
                   chaos plans like every other kind.
- ``lease_store_outage`` / ``lease_store_latency`` — the COORDINATION
                   SERVICE faults (``fleet.replica.LeaseStore``):
                   partition the lease store out from under a live
                   fleet for ``delay_s`` seconds, or make every store
                   round-trip stall. The fleet must degrade fail-safe
                   (serve on unexpired leases, defer membership
                   changes, refuse new admissions past the grace
                   window with classified backpressure) and never
                   split-brain.

Separately, :func:`simulated_vmem` shrinks the VMEM capacity the engine
capacity gates (``fits_resident``/``fits_streamed``) read — so
``select_engine``'s degradation ladder can be walked deterministically —
and :func:`truncate_latest_checkpoint` corrupts an on-disk checkpoint
the way a mid-write kill does, for the quarantine-on-resume path in
``solver.checkpoint``.

Injection happens at guard chunk boundaries: a :class:`FaultPlan` handed
to ``guarded_solve`` makes the guard stop a chunk exactly at each
fault's iteration (``next_stop``) and corrupt the carry there
(``apply``) — deterministic to the iteration, bit-reproducible, and
entirely outside the traced loop (the injected program is the production
program; only the carry between chunks is touched).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time

import jax.numpy as jnp
from jax import lax

FAULT_KINDS = (
    "nan", "breakdown", "stagnation", "halo", "oom",
    "halo_bitflip", "psum_corrupt", "device_loss", "straggler",
    "malformed_spec", "degenerate_geometry",
    "replica_kill", "replica_hang", "lease_clock_skew",
    "lease_store_outage", "lease_store_latency",
    "cache_poison",
)

# dispatch-level faults: consulted by the driver holding the dispatch
# (guard / meshguard / scheduler), never applied to a carry
DISPATCH_KINDS = ("oom", "device_loss", "straggler")

# admission-level faults: consulted by the serve scheduler BEFORE the
# request reaches the queue — they swap the request's geometry spec, so
# the admission gate (geom.validate) is what gets exercised, not a carry
ADMISSION_KINDS = ("malformed_spec", "degenerate_geometry")

# replica-level faults: consulted by the fleet router (fleet.router) at
# arrival boundaries, never by a scheduler or a carry — they kill, hang
# or clock-skew a WHOLE scheduler replica, so the lease/fencing/handoff
# machinery is what gets exercised
REPLICA_KINDS = ("replica_kill", "replica_hang", "lease_clock_skew")

# lease-store faults: consulted by the fleet router at arrival
# boundaries like REPLICA_KINDS, but the target is the COORDINATION
# SERVICE itself (fleet.replica.LeaseStore), not any one replica —
# they partition or slow the store, so the outage grace window /
# deferred-death / recovery-revalidation machinery is what gets
# exercised. ``delay_s`` carries the outage duration (outage) or the
# per-round-trip stall (latency).
LEASE_STORE_KINDS = ("lease_store_outage", "lease_store_latency")

# warm-start faults: consulted by the serve scheduler when it consults
# the solve cache for the addressed request (``serve.scheduler``) — the
# hit (or the empty slot) is replaced with a deliberately WRONG cached
# solution, so the drill exercises the semantic cache's whole defense:
# the true-residual init makes a poisoned x0 cost iterations only, the
# admission check flags it as a ``recycle:bad-hit`` trace event, and
# the answer still converges to the same l2 — never a wrong result,
# never a guard escalation
CACHE_KINDS = ("cache_poison",)


class SimulatedResourceExhausted(RuntimeError):
    """The injected stand-in for a device OOM. Its message carries the
    absl ``RESOURCE_EXHAUSTED`` status marker, so it classifies exactly
    as the real thing (``resilience.errors.classify_error``)."""


class SimulatedDeviceLoss(RuntimeError):
    """The injected stand-in for a dead mesh device under a dispatch.
    The message carries the ``DEVICE_LOST`` marker, so
    ``resilience.errors.is_device_loss_error`` classifies it exactly as
    the real runtime failure; ``device`` names the lost device id for
    the degraded-mesh rebuild."""

    def __init__(self, message: str, device: int | None = None):
        super().__init__(message)
        self.device = device


@dataclasses.dataclass
class Fault:
    """One injected fault: ``kind`` at iteration ``at_iter``.

    ``field`` names the carry field to corrupt (engine-adapter field
    names: classical ``w/r/p/zr``, pipelined ``x/r/u/w/z/s/p/gamma``);
    defaults per kind. ``rows`` is the slab height for ``halo``.
    ``lane`` addresses one lane of a batched carry (``batch.driver``) —
    the corruption lands on that lane's slice only, so the quarantine
    path is exercised against a batch whose other lanes stay healthy;
    ``None`` (single-solve carries) corrupts the whole field.
    ``request_id`` addresses one *in-flight request* of the serve
    scheduler (``serve.scheduler``) instead of a fixed lane index: the
    scheduler resolves it to whichever lane currently hosts that
    request at fire time (``at_iter`` counts the request's OWN
    iterations, not the batch's global clock), so chaos tests can
    poison a specific request across retirement/refill/retry without
    knowing — or caring — where the scheduler packed it. Lane-addressed
    consumers (``batch.driver``) reject request-addressed faults: a
    fixed batch has no request table to resolve against.
    ``fired`` makes every fault one-shot — a replayed chunk after a
    recovery re-runs clean, which is what makes transient-fault recovery
    hit exact oracle parity. ``persistent=True`` re-fires on every visit
    to the iteration instead (the unfixable-fault shape): a restart
    cannot clear it, so the guard is forced up the ladder — precision
    escalation, engine fallback — and finally into the classified error.
    """

    kind: str
    at_iter: int = 0
    field: str | None = None
    rows: int = 1
    lane: int | None = None
    request_id: str | None = None
    fired: bool = False
    persistent: bool = False
    # halo_bitflip addressing: shard index out of ``shards`` blocks along
    # the leading grid axis picks the boundary row; ``bit`` is the flipped
    # bit (None = itemsize*8 - 5, a high-but-not-top exponent bit — the
    # SDC that matters without overflowing the very first inner product
    # to inf; see _flip_bit and the module docstring)
    shard: int = 0
    shards: int = 2
    bit: int | None = None
    # device_loss / straggler: the device id the simulated failure names
    # (the meshguard excludes it from the rebuilt mesh) and the injected
    # straggle duration
    device: int | None = None
    delay_s: float = 0.0
    # degenerate_geometry: the clamp threshold the swapped-in sliver
    # spec carries (None = the quadrature default)
    theta: float | None = None
    # replica-level addressing (fleet.router): ``replica`` names the
    # target replica index; ``at_request`` the fleet arrival index the
    # fault fires at (the fleet's analog of ``at_iter``). ``delay_s``
    # doubles as the hang duration for ``replica_hang``; ``skew_s`` is
    # the injected lease-clock offset for ``lease_clock_skew`` (the
    # NTP-step drill: a skewed replica's renewals land short, so its
    # lease expires under the router's clock while the process lives)
    replica: int = 0
    at_request: int = 0
    skew_s: float = 0.0

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind: {self.kind!r} (one of {FAULT_KINDS})"
            )
        if self.at_iter < 0:
            raise ValueError("at_iter must be >= 0")
        if self.lane is not None and self.request_id is not None:
            raise ValueError(
                "a fault is addressed by lane OR by request_id, not both "
                "(the scheduler resolves request_id to a lane at fire time)"
            )
        if self.kind == "halo_bitflip" and not (
            0 <= self.shard < self.shards
        ):
            raise ValueError(
                f"shard {self.shard} out of range for {self.shards} shards"
            )
        if self.kind == "straggler" and self.delay_s < 0:
            raise ValueError("delay_s must be >= 0")
        if self.kind in REPLICA_KINDS:
            if self.replica < 0:
                raise ValueError("replica must be >= 0")
            if self.at_request < 0:
                raise ValueError("at_request must be >= 0")
            if self.kind == "replica_hang" and self.delay_s < 0:
                raise ValueError("delay_s must be >= 0")
        if self.kind in LEASE_STORE_KINDS:
            if self.at_request < 0:
                raise ValueError("at_request must be >= 0")
            if self.delay_s <= 0:
                raise ValueError(
                    "lease-store faults need delay_s > 0 (the outage "
                    "duration or the per-round-trip stall)"
                )


def inject_nan(at_iter: int, field: str = "r",
               lane: int | None = None) -> Fault:
    """NaN-poison carry field ``field`` at iteration ``at_iter`` —
    optionally only lane ``lane`` of a batched carry."""
    return Fault("nan", at_iter=at_iter, field=field, lane=lane)


def force_breakdown(at_iter: int) -> Fault:
    """Raise the breakdown flag at iteration ``at_iter``."""
    return Fault("breakdown", at_iter=at_iter)


def inject_stagnation(at_iter: int) -> Fault:
    """Corrupt the carried zr/γ so the solve stops progressing."""
    return Fault("stagnation", at_iter=at_iter)


def corrupt_halo(at_iter: int, field: str = "r", rows: int = 1) -> Fault:
    """NaN a ``rows``-high halo slab of ``field`` at ``at_iter``."""
    return Fault("halo", at_iter=at_iter, field=field, rows=rows)


def simulate_oom(at_iter: int = 0) -> Fault:
    """Raise a RESOURCE_EXHAUSTED-classified error at ``at_iter``."""
    return Fault("oom", at_iter=at_iter)


def halo_bitflip(at_iter: int, field: str = "r", shard: int = 1,
                 shards: int = 2, bit: int | None = None,
                 lane: int | None = None,
                 persistent: bool = False) -> Fault:
    """Flip one bit of one element of ``field`` at shard ``shard``'s
    boundary row — the silent-corruption fault (seed-free deterministic:
    same carry in, same flipped bit out). The default ``shard=1`` puts
    the flip on an interior shard-boundary row; shard 0's first row is
    the Dirichlet ring, where every iterate is exactly 0.0 and a flip
    is both numerically inert and below the detection model."""
    return Fault(
        "halo_bitflip", at_iter=at_iter, field=field, shard=shard,
        shards=shards, bit=bit, lane=lane, persistent=persistent,
    )


def psum_corrupt(at_iter: int, lane: int | None = None) -> Fault:
    """Flip the sign of the carried ⟨z, r⟩ — a corrupted all-reduce."""
    return Fault("psum_corrupt", at_iter=at_iter, lane=lane)


def device_loss(chunk: int = 0, device: int = 0) -> Fault:
    """Raise a DEVICE_LOST-classified error at chunk-boundary iteration
    ``chunk`` naming ``device`` as the casualty."""
    return Fault("device_loss", at_iter=chunk, device=device)


def straggler(delay_s: float, at_iter: int = 0,
              device: int | None = None) -> Fault:
    """Sleep ``delay_s`` at the chunk boundary — the slow-device shape
    the per-chunk deadline detects."""
    return Fault("straggler", at_iter=at_iter, delay_s=delay_s,
                 device=device)


def malformed_spec(request_id: str | None = None) -> Fault:
    """Swap the addressed request's geometry for an unparseable spec at
    ADMISSION — what a corrupted/hostile client payload looks like to
    the serving layer. The admission gate must reject it with the
    classified ``invalid`` outcome (exit 8) before it touches a lane."""
    return Fault("malformed_spec", request_id=request_id)


def degenerate_geometry(theta: float | None = None,
                        request_id: str | None = None) -> Fault:
    """Swap the addressed request's geometry for the canonical
    sliver-cut domain (:func:`sliver_spec`) at ADMISSION, carrying
    clamp threshold ``theta``. With the degenerate-cut defense on
    (``theta`` at its default) the request must SOLVE cleanly — the
    drill asserts the clamp, not a rejection."""
    return Fault("degenerate_geometry", request_id=request_id, theta=theta)


def cache_poison(request_id: str | None = None) -> Fault:
    """Replace the addressed request's solve-cache consult with a
    deliberately wrong cached solution (:func:`poisoned_guess`) — the
    stale/corrupted-cache-entry drill. The scheduler's warm-start
    admission must flag it (``recycle:bad-hit``) and the solve must
    still converge to the same l2, with extra iterations as the only
    cost (the semantic cache's correctness contract)."""
    return Fault("cache_poison", request_id=request_id)


def poisoned_guess(shape, np_dtype):
    """The deterministic wrong warm start ``cache_poison`` injects: a
    large-amplitude checkerboard (boundary ring included — the init's
    interior mask must neutralise it). Far from ANY smooth Poisson
    solution, so the bad-hit ratio check trips unambiguously, and
    seed-free deterministic so replays of the drill are bit-identical."""
    import numpy as np

    idx = np.indices(shape).sum(axis=0)
    return (np.where(idx % 2 == 0, 1e3, -1e3)).astype(np_dtype)


def replica_kill(at_request: int = 0, replica: int = 0) -> Fault:
    """SIGKILL one scheduler replica of the fleet when arrival
    ``at_request`` lands: its process object is dropped with requests
    queued and in flight, its fencing token is revoked, and its journal
    is handed off to the survivors (``fleet.handoff``). The fleet chaos
    invariants (zero lost / zero double / all classified) are what the
    drill asserts."""
    return Fault("replica_kill", at_request=at_request, replica=replica)


def replica_hang(delay_s: float = float("inf"), at_request: int = 0,
                 replica: int = 0) -> Fault:
    """The zombie drill: the replica's PROCESS stays alive but stops
    heartbeating (and stepping) for ``delay_s`` seconds from arrival
    ``at_request``. Its lease expires under the router's clock, it is
    declared dead and fenced, its work is handed off — and when the
    zombie resurrects mid-handoff and tries to complete a request, the
    fenced journal write MUST be rejected (the zero-double pin)."""
    return Fault("replica_hang", at_request=at_request, replica=replica,
                 delay_s=delay_s)


def lease_clock_skew(skew_s: float, at_request: int = 0,
                     replica: int = 0) -> Fault:
    """The NTP-step drill: from arrival ``at_request`` the replica's
    lease renewals are computed on a clock ``skew_s`` seconds behind the
    router's, so every renewed deadline lands short. A skew past the
    lease length makes a perfectly healthy replica read as expired —
    the router must fence it (stale writes rejected, work handed off)
    rather than let two replicas both believe they own the requests."""
    return Fault("lease_clock_skew", at_request=at_request,
                 replica=replica, skew_s=skew_s)


def lease_store_outage(duration_s: float, at_request: int = 0) -> Fault:
    """Partition the lease store out from under the fleet for
    ``duration_s`` seconds from arrival ``at_request``: every store
    round-trip (issue / fence / ping / refresh) raises
    ``LeaseStoreOutageError`` until the duration passes. Replicas
    holding unexpired leases keep serving (epoch VALIDATION answers
    from the local cache — fail-safe, not fail-open), deaths detected
    during the outage are deferred until the store answers again, and
    admissions past the router's grace window are refused with
    classified, capped-exponential backpressure — never a hang, never
    split-brain ownership."""
    return Fault("lease_store_outage", at_request=at_request,
                 delay_s=duration_s)


def lease_store_latency(delay_s: float, at_request: int = 0) -> Fault:
    """The slow-quorum drill: from arrival ``at_request`` every lease
    store round-trip stalls ``delay_s`` first (sticky, not one-shot in
    effect — the latency stays armed once applied). Membership changes
    get slower; the steady-state write path (fenced journal writes,
    epoch validation) must NOT, because validation never round-trips."""
    return Fault("lease_store_latency", at_request=at_request,
                 delay_s=delay_s)


MALFORMED_SPEC = {"kind": "dodecahedron", "r": -1.0}


def sliver_spec(gap_frac: float = 1e-3) -> dict:
    """The canonical degenerate-cut domain: the reference ellipse with a
    crack comb of internal slits ``gap_frac`` of a cell wide. Every
    slit-crossing face gets fraction 1 − gap_frac, whose blend
    coefficient 1 + gap_frac/ε is an artificial stiff rod INSIDE the
    domain — unclamped, diag-PCG measurably stalls on it; clamped
    (θ > gap_frac), the slits snap to full faces and the solve is the
    plain ellipse's (the defense ``tests/test_geom.py`` measures)."""
    # slit centers deliberately off every coarse grid's node lines (the
    # chaos grids are 8-12 cells: node spacings 0.1/0.12/0.15): a slit
    # that swallows a node ROW is under-resolved by the gate's own rules
    # — the drill wants the gate to PASS and the clamp to defend
    half = 0.0006 * gap_frac / 1e-3
    slits = [
        {"kind": "rectangle", "x0": -0.9, "x1": 0.9,
         "y0": 0.017 + 0.123 * k - half,
         "y1": 0.017 + 0.123 * k + half}
        for k in (-2, -1, 0, 1, 2)
    ]
    return {
        "kind": "difference",
        "a": {"kind": "ellipse"},
        "b": {"kind": "union", "shapes": slits},
    }


class FaultPlan:
    """An ordered set of one-shot faults the guard consults at chunk
    boundaries. Empty plan = production behaviour (the guard's healthy
    path does not depend on the plan's presence)."""

    def __init__(self, *faults: Fault):
        self.faults = list(faults)

    def __bool__(self) -> bool:
        return any(not f.fired for f in self.faults)

    def next_stop(self, k: int) -> int | None:
        """The earliest unfired fault iteration strictly past ``k`` —
        the guard caps its next chunk there so injection lands on an
        exact iteration, not somewhere inside a chunk."""
        pending = [f.at_iter for f in self.faults if not f.fired and f.at_iter > k]
        return min(pending) if pending else None

    def apply(self, k: int, state, fields: dict[str, int], breakdown_index: int,
              zr_index: int):
        """Fire every unfired fault scheduled at iteration ``k`` against
        ``state`` (an engine carry tuple); returns the corrupted carry.
        ``oom`` faults raise :class:`SimulatedResourceExhausted` instead,
        exactly where a real dispatch would."""
        for fault in self.faults:
            if fault.fired or fault.at_iter != k:
                continue
            if not fault.persistent:
                fault.fired = True
            if fault.kind == "oom":
                raise SimulatedResourceExhausted(
                    "RESOURCE_EXHAUSTED: simulated device OOM "
                    f"(fault injection at iteration {k})"
                )
            if fault.kind == "device_loss":
                raise SimulatedDeviceLoss(
                    f"DEVICE_LOST: simulated loss of device "
                    f"{fault.device} (fault injection at iteration {k})",
                    device=fault.device,
                )
            if fault.kind == "straggler":
                # the slow-device shape: the boundary's dispatch is late
                # by delay_s, so a per-chunk deadline trips on it
                time.sleep(fault.delay_s)
                continue
            state = _corrupt(state, fault, fields, breakdown_index, zr_index)
        return state

    def lost_devices(self) -> list[int]:
        """Device ids named by fired device_loss/straggler faults — the
        exclusion list the degraded-mesh rebuild consults."""
        return [
            f.device
            for f in self.faults
            if f.fired and f.kind in ("device_loss", "straggler")
            and f.device is not None
        ]


def _flip_bit(value, bit: int | None):
    """Flip one bit of a floating scalar, deterministically: bitcast to
    the same-width integer, XOR, bitcast back. ``bit=None`` picks
    itemsize·8 − 2 — a high exponent bit, the corruption magnitude class
    the ABFT checksums are specified to catch."""
    value = jnp.asarray(value)
    width = value.dtype.itemsize * 8
    if bit is None:
        # a high exponent bit — catastrophic (×2^128 in f64, ×2^16 in
        # f32) but NOT the top one: flipping the exponent MSB overflows
        # the very first inner product to inf, which reads as nonfinite
        # rather than exercising the checksum classification
        bit = width - 5
    if not 0 <= bit < width:
        raise ValueError(f"bit {bit} out of range for {value.dtype}")
    int_dtype = {16: jnp.int16, 32: jnp.int32, 64: jnp.int64}[width]
    as_int = lax.bitcast_convert_type(value, int_dtype)
    flipped = as_int ^ jnp.asarray(1 << bit, int_dtype)
    return lax.bitcast_convert_type(flipped, value.dtype)


def _bitflip_site(arr, fault: Fault) -> tuple[int, int]:
    """(row, col) of the flipped element: shard ``shard``'s first block
    row (its receive-side halo boundary) at the middle column —
    deterministic in the fault alone."""
    rows, cols = arr.shape[-2], arr.shape[-1]
    row = min((rows // fault.shards) * fault.shard, rows - 1)
    return row, cols // 2


def _corrupt(state, fault: Fault, fields: dict[str, int],
             breakdown_index: int, zr_index: int):
    state = list(state)
    if fault.lane is not None:
        return _corrupt_lane(state, fault, fields, breakdown_index, zr_index)
    if fault.kind == "psum_corrupt":
        zr = state[zr_index]
        state[zr_index] = -zr  # exactly a sign-bit (bit 31/63) flip
        return tuple(state)
    if fault.kind == "halo_bitflip":
        field = fault.field or "r"
        if field not in fields:
            raise ValueError(
                f"engine carry has no field {field!r} (has {sorted(fields)})"
            )
        idx = fields[field]
        arr = state[idx]
        row, col = _bitflip_site(arr, fault)
        state[idx] = arr.at[row, col].set(_flip_bit(arr[row, col], fault.bit))
        return tuple(state)
    if fault.kind == "breakdown":
        state[breakdown_index] = jnp.asarray(True)
    elif fault.kind == "stagnation":
        if "s" in fields:
            # pipelined carry: corrupt the recurrence-maintained s = A·p.
            # The drifted recurrence then satisfies the step-norm stopping
            # rule at a garbage iterate (α collapses, diff → 0) — the
            # false-convergence form of stagnation the guard's residual-
            # drift check exists for.
            s = state[fields["s"]]
            state[fields["s"]] = jnp.full_like(s, 1e12)
        else:
            # classical carry: blow the carried zr — the next α is
            # garbage, the iterates jump far from the solution, and the
            # solve stops progressing.
            zr = state[zr_index]
            state[zr_index] = jnp.asarray(1e30, zr.dtype)
    elif fault.kind in ("nan", "halo"):
        field = fault.field or "r"
        if field not in fields:
            raise ValueError(
                f"engine carry has no field {field!r} (has {sorted(fields)})"
            )
        idx = fields[field]
        arr = state[idx]
        if fault.kind == "nan":
            state[idx] = jnp.full_like(arr, jnp.nan)
        else:
            state[idx] = arr.at[: fault.rows].set(jnp.nan)
    return tuple(state)


def _corrupt_lane(state, fault: Fault, fields: dict[str, int],
                  breakdown_index: int, zr_index: int):
    """Lane-addressed corruption of a batched carry: only slice
    ``fault.lane`` of the named field/flag is touched, so the rest of
    the batch runs clean past the fault (the quarantine contract)."""
    lane = fault.lane
    if fault.kind == "psum_corrupt":
        zr = state[zr_index]
        state[zr_index] = zr.at[lane].set(-zr[lane])
        return tuple(state)
    if fault.kind == "halo_bitflip":
        field = fault.field or "r"
        if field not in fields:
            raise ValueError(
                f"engine carry has no field {field!r} (has {sorted(fields)})"
            )
        idx = fields[field]
        arr = state[idx]
        row, col = _bitflip_site(arr, fault)
        state[idx] = arr.at[lane, row, col].set(
            _flip_bit(arr[lane, row, col], fault.bit)
        )
        return tuple(state)
    if fault.kind == "breakdown":
        flags = state[breakdown_index]
        state[breakdown_index] = flags.at[lane].set(True)
    elif fault.kind == "stagnation":
        zr = state[zr_index]
        state[zr_index] = zr.at[lane].set(jnp.asarray(1e30, zr.dtype))
    elif fault.kind in ("nan", "halo"):
        field = fault.field or "r"
        if field not in fields:
            raise ValueError(
                f"engine carry has no field {field!r} (has {sorted(fields)})"
            )
        idx = fields[field]
        arr = state[idx]
        if fault.kind == "halo" and arr.ndim < 3:
            raise ValueError(
                f"field {field!r} is not a lane-stacked grid; halo "
                "faults need a (B, g1, g2) carry field"
            )
        if fault.kind == "nan":
            state[idx] = arr.at[lane].set(jnp.nan)
        else:
            state[idx] = arr.at[lane, : fault.rows].set(jnp.nan)
    return tuple(state)


@contextlib.contextmanager
def simulated_vmem(capacity_bytes: int):
    """Shrink the VMEM capacity every engine capacity gate sees.

    Inside the context, ``fits_resident``/``fits_streamed`` (and with
    them ``select_engine``) budget against ``capacity_bytes`` instead of
    the device table — the deterministic stand-in for running on a part
    too small for the picked engine."""
    from poisson_ellipse_tpu.utils.device import vmem_capacity_override

    with vmem_capacity_override(capacity_bytes):
        yield


def truncate_latest_checkpoint(directory: str) -> str:
    """Truncate the largest file of the newest checkpoint step in
    ``directory`` to half its size — the on-disk shape of a kill during
    a checkpoint write. Returns the truncated path.

    Used by the quarantine-on-resume tests of ``solver.checkpoint``: a
    resume over this damage must fall back to the previous step, not
    crash mid-restore.
    """
    steps = [
        name for name in os.listdir(directory)
        if name.isdigit() and os.path.isdir(os.path.join(directory, name))
    ]
    if not steps:
        raise FileNotFoundError(f"no checkpoint steps under {directory}")
    step_dir = os.path.join(directory, max(steps, key=int))
    largest, size = None, -1
    for dirpath, _dirnames, filenames in os.walk(step_dir):
        for name in filenames:
            path = os.path.join(dirpath, name)
            n = os.path.getsize(path)
            if n > size:
                largest, size = path, n
    if largest is None:
        raise FileNotFoundError(f"no files under {step_dir}")
    with open(largest, "r+b") as fh:
        fh.truncate(size // 2)
    return largest
