"""One fleet replica: a Scheduler + its own journal under a lease.

The failure model the fleet is built against is the standard distributed
one: a replica can die (SIGKILL), hang (alive but not making progress),
or run on a skewed clock — and in every case the rest of the fleet must
agree on ONE owner per request. Two mechanisms carry that agreement:

- **Lease** — a monotonic-clock heartbeat the replica renews at chunk
  boundaries (``Replica.step``). A replica that misses its deadline is
  *declared dead by the router* (``fleet.router``); the replica itself
  never gets a vote, because a hung process cannot be trusted to report
  its own hang. Wall-clock leases are a bug class of their own (an NTP
  step makes them fire early or never — tpulint TPU016 fences the
  pattern), so every lease arithmetic here is ``clock()`` =
  ``time.monotonic`` by default.

- **Fencing token** — an epoch issued by the fleet's
  :class:`FenceAuthority` when the replica is born and revoked the
  instant it is declared dead. The replica's journal carries the token
  (``serve.journal.RequestJournal(fence=...)``): every journal write
  validates it first and every snapshot embeds it, so a zombie — a
  replica whose lease expired while its process lived — that resurrects
  mid-handoff and tries to admit or complete a request hits
  :class:`StaleLeaseError` at the journal, before anything lands in
  memory or on disk. Zero-double is enforced where the record lives,
  not asserted after the fact (the ``serve.journal`` stance, promoted
  fleet-wide).

The replica's scheduler is the unmodified ``serve.Scheduler`` — same
admission, same retry ladder, same chunk-boundary retire/refill. The
fleet wraps it; it does not fork it.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Callable, Optional

from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.resilience.errors import (
    LeaseStoreCorruptError,
    LeaseStoreOutageError,
)
from poisson_ellipse_tpu.serve.journal import RequestJournal
from poisson_ellipse_tpu.serve.scheduler import Scheduler

DEFAULT_LEASE_S = 0.5


def routing_load_key(rep: "Replica", affinity_key) -> tuple:
    """The fleet's shared routing order (router admission AND handoff
    adoption): replicas with free lanes first (load quantized by lane
    width), warm compile-bucket affinity within a load class, then raw
    load, then id for determinism. Quantizing load by lanes is what
    keeps affinity from defeating scaling — a warm replica wins ties,
    but a replica with free lanes always beats a saturated warm one."""
    load = rep.queue_depth() + rep.in_flight()
    lanes = max(rep.scheduler.lanes, 1)
    return (
        load // lanes,
        0 if affinity_key in rep.warm_keys() else 1,
        load,
        rep.replica_id,
    )


class StaleLeaseError(RuntimeError):
    """A fenced (revoked) token tried to write: the zombie-resurrection
    bug class — a replica declared dead coming back mid-handoff and
    double-completing a request a survivor now owns. Raised by
    :meth:`FencingToken.check` at the journal choke point, trace-evented
    (``fleet:stale-write-rejected``) and counted
    (``fleet_stale_writes_total``) so the drill is observable, not
    silent."""


class LeaseStore:
    """The fleet's epoch registry AND its own fault domain.

    One current epoch per replica id; :meth:`issue` mints a token at a
    fresh epoch, :meth:`fence` advances the epoch so every outstanding
    token goes stale atomically, and :meth:`valid` is the single
    comparison every fenced write reduces to.

    The store is the stand-in for the lease service a multi-host
    deployment would put behind etcd/Chubby — which means the store
    itself can fail, and the failure semantics are the design:

    - operations that must ROUND-TRIP to the store (:meth:`issue`,
      :meth:`fence`, :meth:`ping`, :meth:`refresh`) pass through
      :meth:`_gate`, where injected latency (``delay_for`` /
      ``faultinject.lease_store_latency``) and outage (``fail_for`` /
      ``faultinject.lease_store_outage``) apply; during an outage they
      raise :class:`~poisson_ellipse_tpu.resilience.errors.LeaseStoreOutageError`.
    - :meth:`valid` is deliberately NOT gated: it answers from the
      local cache mirror, so replicas holding unexpired leases keep
      serving (and zombies keep getting rejected) straight through a
      store outage. The fleet degrades on *membership change*, never on
      the steady-state write path.

    ``on_delay`` is the sleep hook injected latency uses (the router
    points it at its own ``idle`` so FakeClock tests stay honest).
    A ``threading.Lock`` serialises epoch mutation: concurrent
    issue/revoke interleavings must observe strictly monotonic epochs.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self.on_delay: Optional[Callable[[float], None]] = None
        self._outage_until = 0.0
        self._latency_s = 0.0
        self._lock = threading.Lock()
        self._epoch: dict[int, int] = {}

    # -- fault surface (faultinject.lease_store_* lands here) ---------------

    def fail_for(self, duration_s: float) -> None:
        """Arm an outage: every gated round-trip raises until
        ``duration_s`` of store-clock time passes."""
        self._outage_until = self.clock() + float(duration_s)

    def delay_for(self, delay_s: float) -> None:
        """Arm sticky latency: every gated round-trip stalls
        ``delay_s`` first (the slow-quorum drill)."""
        self._latency_s = max(0.0, float(delay_s))

    def _gate(self, op: str) -> None:
        if self._latency_s > 0.0:
            (self.on_delay or time.sleep)(self._latency_s)
        if self.clock() < self._outage_until:
            raise LeaseStoreOutageError(
                f"lease store unreachable: '{op}' refused for another "
                f"{self._outage_until - self.clock():.3f}s"
            )

    def ping(self) -> None:
        """A gated no-op round-trip: the router's recovery probe."""
        self._gate("ping")

    # -- the epoch registry -------------------------------------------------

    def issue(self, replica_id: int) -> "FencingToken":
        """Mint the replica's token at a fresh epoch (re-issuing — a
        restarted or REJOINING replica under the same id — bumps the
        epoch, so the dead incarnation's token is stale from the first
        write). Round-trips: raises during an outage, which is exactly
        right — a fleet that cannot reach its lease store must not
        mint new incarnations."""
        self._gate("issue")
        with self._lock:
            epoch = self._epoch.get(replica_id, 0) + 1
            self._epoch[replica_id] = epoch
            self._persist()
        return FencingToken(self, replica_id, epoch)

    def fence(self, replica_id: int) -> None:
        """Revoke every outstanding token of ``replica_id`` (declared
        dead): the epoch advances, so the zombie's next fenced write
        raises instead of landing. Round-trips (raises during an
        outage): the router defers the death until the store answers."""
        self._gate("fence")
        with self._lock:
            self._epoch[replica_id] = self._epoch.get(replica_id, 0) + 1
            self._persist()

    def valid(self, replica_id: int, epoch: int) -> bool:
        """UNGATED — answers from the local cache mirror (see class
        docstring): journal writes validate at full speed through an
        outage."""
        return self._epoch.get(replica_id) == epoch

    def refresh(self) -> None:
        """Re-read persisted state after an outage (gated). In-process
        stores have nothing to re-read; the file-backed impl reloads
        and classifies corruption."""
        self._gate("refresh")

    def current_epoch(self, replica_id: int) -> int:
        return self._epoch.get(replica_id, 0)

    def _persist(self) -> None:
        """Write-through hook, called under ``_lock`` after every epoch
        mutation. In-process: nothing to do."""


class FenceAuthority(LeaseStore):
    """The in-process :class:`LeaseStore` — the fleet default.

    Kept under its PR 12 name: the epoch registry semantics are
    unchanged, it just sits on the pluggable store surface now (gated
    round-trips, fault hooks, locked mutation) so chaos can partition
    the coordination service out from under a live fleet."""


class FileLeaseStore(LeaseStore):
    """A file-backed :class:`LeaseStore`: the cross-process stand-in.

    Epochs persist as one JSON document written atomically (temp file
    in the same directory, fsync, then ``os.replace`` — the
    ``serve.journal`` discipline, so a crash mid-write leaves the OLD
    complete state, never a torn one). Reads that DO find a torn or
    truncated document — an external writer without the atomic
    discipline, bit rot — raise
    :class:`~poisson_ellipse_tpu.resilience.errors.LeaseStoreCorruptError`
    instead of re-initialising: silently resetting epochs would
    validate a fenced zombie's stale token again, which is split-brain
    by construction. A missing file is a FRESH store (first boot), not
    corruption."""

    def __init__(self, path, clock: Callable[[], float] = time.monotonic):
        super().__init__(clock=clock)
        self.path = os.fspath(path)
        self._epoch = self._load()

    def _load(self) -> dict[int, int]:
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                raw = fh.read()
        except FileNotFoundError:
            return {}
        try:
            doc = json.loads(raw)
        except ValueError as exc:
            raise LeaseStoreCorruptError(
                f"lease store {self.path} failed to parse ({exc}): torn "
                "write or truncation; refusing to re-initialise epochs "
                "(a reset would re-validate fenced tokens — split-brain)"
            ) from exc
        if not isinstance(doc, dict) or not isinstance(doc.get("epoch"), dict):
            raise LeaseStoreCorruptError(
                f"lease store {self.path} parsed but lacks the epoch "
                "table; refusing to re-initialise"
            )
        return {int(k): int(v) for k, v in doc["epoch"].items()}

    def _persist(self) -> None:
        doc = {
            "v": 1,
            "epoch": {str(k): v for k, v in sorted(self._epoch.items())},
        }
        dirname = os.path.dirname(self.path) or "."
        fd, tmp = tempfile.mkstemp(
            dir=dirname, prefix=".lease-store.", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                fh.write(json.dumps(doc, sort_keys=True))
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def refresh(self) -> None:
        """Reload the persisted epoch table (gated): the router calls
        this first thing at outage recovery so every lease re-validates
        against what the STORE says, not what this process remembers.
        Epochs only ever advance, so the merged view takes the max of
        disk and cache per replica."""
        self._gate("refresh")
        with self._lock:
            disk = self._load()
            for rid, epoch in disk.items():
                if epoch > self._epoch.get(rid, 0):
                    self._epoch[rid] = epoch


class FencingToken:
    """One replica incarnation's write credential: ``(replica, epoch)``.

    ``value`` is the string every journal snapshot embeds;
    :meth:`check` is the gate every journal mutation calls first."""

    __slots__ = ("authority", "replica_id", "epoch")

    def __init__(self, authority: "LeaseStore", replica_id: int,
                 epoch: int):
        self.authority = authority
        self.replica_id = replica_id
        self.epoch = epoch

    @property
    def value(self) -> str:
        return f"r{self.replica_id}:e{self.epoch}"

    @property
    def stale(self) -> bool:
        return not self.authority.valid(self.replica_id, self.epoch)

    def check(self) -> None:
        """Raise :class:`StaleLeaseError` (trace-evented, counted) when
        the token has been fenced — the zero-double choke point."""
        if self.stale:
            obs_trace.event(
                "fleet:stale-write-rejected",
                replica=self.replica_id,
                token=self.value,
            )
            obs_metrics.counter(
                obs_metrics.FLEET_STALE_WRITES_TOTAL
            ).inc()
            raise StaleLeaseError(
                f"fencing token {self.value} is stale: replica "
                f"{self.replica_id} was declared dead and fenced; this "
                "write belongs to a zombie and is rejected"
            )


class Lease:
    """A monotonic-clock lease: ``renew()`` pushes the deadline
    ``lease_s`` ahead of now; a missed renewal lets ``expired(now)``
    trip under the ROUTER's clock. ``skew_s`` injects the NTP-step
    drill (``faultinject.lease_clock_skew``): the replica's renewals
    are computed on a clock ``skew_s`` behind the router's, so a skew
    past the lease length makes a live replica read as dead — the
    router must fence it rather than share ownership."""

    def __init__(self, clock: Callable[[], float], lease_s: float):
        if lease_s <= 0:
            raise ValueError("lease_s must be > 0")
        self.clock = clock
        self.lease_s = lease_s
        self.skew_s = 0.0
        self.deadline = clock() + lease_s

    def renew(self) -> None:
        self.deadline = self.clock() - self.skew_s + self.lease_s

    def remaining(self, now: float) -> float:
        return self.deadline - now

    def expired(self, now: float) -> bool:
        return now > self.deadline


class Replica:
    """One scheduler replica of the fleet: ``Scheduler`` + fenced
    journal + lease, plus the drain/handoff surface the router drives.

    ``journal_path`` is this replica's own ledger (one file per
    replica: a fleet shares NO mutable state except the fence
    authority, which stands in for the shared lease store).
    ``scheduler_kw`` passes through to ``serve.Scheduler`` untouched.
    """

    def __init__(
        self,
        replica_id: int,
        journal_path,
        authority: LeaseStore,
        clock: Callable[[], float] = time.monotonic,
        lease_s: float = DEFAULT_LEASE_S,
        **scheduler_kw,
    ):
        self.replica_id = replica_id
        self.journal_path = journal_path
        self.authority = authority
        self.clock = clock
        self.token = authority.issue(replica_id)
        self.lease = Lease(clock, lease_s)
        self.scheduler = Scheduler(
            journal=RequestJournal(journal_path, fence=self.token),
            clock=clock,
            **scheduler_kw,
        )
        # a hang fault parks the heartbeat until this instant while the
        # process object lives — the zombie drill's arming state
        self.hung_until: float = 0.0
        self.dead = False

    # -- the router-facing surface ------------------------------------------

    @property
    def live(self) -> bool:
        return not self.dead

    @property
    def draining(self) -> bool:
        return self.scheduler.draining

    def queue_depth(self) -> int:
        return len(self.scheduler.queue) + len(
            self.scheduler._replay_backlog
        )

    def in_flight(self) -> int:
        return sum(
            1
            for ctx in self.scheduler._ctxs.values()
            for slot in ctx.slots
            if slot is not None
        )

    def warm_keys(self) -> frozenset:
        """The compile-bucket keys this replica holds LIVE batch
        contexts for — ``runtime.compile_cache.warm_affinity_key``'s
        ``(grid_bucket, norm)`` spelling, which is exactly the
        scheduler's ``_ctxs`` key. The router's affinity signal."""
        return frozenset(self.scheduler._ctxs.keys())

    def hung(self, now: float) -> bool:
        return now < self.hung_until

    def step(self, now: Optional[float] = None) -> bool:
        """One chunk boundary: advance the scheduler (a hung or dead
        replica does nothing). The lease renewal is NOT here — it is
        the router's post-step sweep (``FleetRouter.step``), the one
        authoritative site, stamped AFTER the work so the heartbeat
        means "made progress", not "was about to"; a scheduler wedged
        inside a dispatch never reaches the sweep and stops
        heartbeating, which is the property the lease exists for."""
        now = self.clock() if now is None else now
        if self.dead or self.hung(now):
            return False
        return self.scheduler.step()

    def resurrect_step(self) -> bool:
        """What a ZOMBIE's own serve loop does when the hang clears: it
        does not know the router declared it dead, so it steps its
        scheduler directly — and the moment a lane retires, the fenced
        journal raises :class:`StaleLeaseError` before the completion
        can land anywhere. The drill entry (``serve.chaos`` /
        ``tests/test_fleet.py``); the router never calls this."""
        return self.scheduler.step()

    def begin_drain(self) -> None:
        self.scheduler.begin_drain()

    def publish_metrics(self) -> None:
        obs_metrics.replica_gauge("fleet_queue_depth", self.replica_id).set(
            self.queue_depth()
        )
        obs_metrics.replica_gauge("fleet_in_flight", self.replica_id).set(
            self.in_flight()
        )
