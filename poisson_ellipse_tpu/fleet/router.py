"""Shape-aware, health-checked routing over N scheduler replicas.

The router is the fleet's front door and its failure detector in one
loop — the crash-safety ladder, in the order a request experiences it:

1. **Route warm** — admission prefers a live replica already holding the
   request's compile bucket (``runtime.compile_cache.warm_affinity_key``
   == the scheduler's batch-context key), then least-loaded. A request
   lands where its executable is warm; only a cold bucket pays a
   compile, and only once per fleet, not once per replica.
2. **Honor backpressure** — a replica that sheds (queue full, draining,
   infeasible deadline) answers with ``retry_after_s``; the router
   tries the next candidate and only returns a terminal shed (with the
   MINIMUM retry hint — the soonest anyone frees up) when every live
   replica refused.
3. **Hedge around suspects** — a replica whose lease is inside the
   hedge margin of expiry is *suspected*: new requests route around it
   (``fleet:hedge`` trace event) rather than queue behind a process
   that is probably dying. Suspicion is cheap and reversible; death is
   neither, so the thresholds differ.
4. **Declare, fence, hand off** — a replica that misses its lease
   deadline is declared dead under the ROUTER's monotonic clock: its
   fencing token is revoked FIRST (zombie writes now rejected), then
   its journal replays into the survivors (``fleet.handoff``) with
   remaining-deadline budgets preserved. Queued and in-flight requests
   re-enter exactly once; completed ones were compacted and do not.
5. **Classify total loss** — with zero live, non-draining replicas the
   router raises ``FleetUnavailableError`` (exit 9, carrying a
   ``retry_after_s`` hint) instead of hanging a request on a queue
   nobody will drain. One replica down is routine; all replicas down is
   loud.

Drain (``shutdown()``) is the graceful inverse: every replica stops
admitting (``Scheduler.begin_drain``), finishes what it owns, flushes
metrics — the SIGTERM path of ``harness serve``/``harness fleet`` rides
this hook.

Two survivability layers ride on the same loop:

- **REJOIN** (``rejoin_replica``) makes membership elastic upward: a
  dead or zombie-fenced replica re-enters as a FRESH incarnation — new
  epoch from the lease store, its old journal archived and replayed
  through the handoff adoption path *before* the new incarnation takes
  traffic (anything a live owner already holds is skipped, so no
  request is ever co-owned across epochs), and its batch contexts
  pre-warmed from the router's observed shape mix so the first real
  requests land warm.
- **Lease-store outage handling**: the store itself is a fault domain
  (``faultinject.lease_store_outage``). During an outage the fleet is
  fail-safe, not fail-open — replicas holding unexpired leases keep
  serving (epoch validation answers from the local cache), deaths that
  need a fence round-trip are DEFERRED until the store answers, and new
  admissions are allowed only within ``store_grace_s`` of the outage
  start; past the grace window every submit raises a classified
  ``FleetUnavailableError`` whose ``retry_after_s`` backs off
  exponentially (capped — the TPU014 discipline). Recovery re-validates
  every live lease against the store before admission resumes, then
  completes the deferred fences and handoffs.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

from poisson_ellipse_tpu.fleet.handoff import handoff_journal
from poisson_ellipse_tpu.fleet.replica import (
    DEFAULT_LEASE_S,
    FenceAuthority,
    LeaseStore,
    Replica,
    routing_load_key,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.resilience.errors import (
    FleetUnavailableError,
    LeaseStoreError,
)
from poisson_ellipse_tpu.resilience.faultinject import (
    LEASE_STORE_KINDS,
    REPLICA_KINDS,
    FaultPlan,
)
from poisson_ellipse_tpu.runtime.compile_cache import warm_affinity_key
from poisson_ellipse_tpu.serve.request import (
    ServeRequest,
    ServeResult,
    new_request_id,
)

# fraction of the lease length left below which a replica is SUSPECTED
# (new requests hedge around it); 0 disables hedging
DEFAULT_HEDGE_FRAC = 0.25

# store_grace_s default, in lease lengths: how long past an outage start
# the fleet keeps admitting on unexpired leases before failing safe
DEFAULT_STORE_GRACE_LEASES = 2.0

# cap on the outage-refusal exponential backoff, in lease lengths (the
# TPU014 discipline: bounded, never a runaway doubling)
STORE_BACKOFF_CAP_LEASES = 16.0

# how many distinct shape-mix buckets a rejoin pre-warms (most-observed
# first): enough to cover a typical serving mix without compiling the
# long tail on the rejoin path
DEFAULT_PREWARM_BUCKETS = 4

# retired incarnations kept addressable (duplicate-gate memory, live
# counters); older ones are evicted with their counters folded into
# aggregates — a fleet rejoining forever must not accumulate schedulers
# (the TPU012 bound, same windowed idiom as obs.metrics.Histogram)
RETIRED_INCARNATIONS_KEPT = 64


class FleetRouter:
    """N replicas behind one admission surface (see module docstring).

    ``journal_dir`` holds one ledger per replica
    (``replica-<i>.journal``); ``clock`` must be monotonic (injectable
    for deterministic lease tests); ``faults`` takes replica-addressed
    injections (``faultinject.replica_kill/replica_hang/
    lease_clock_skew``) consulted at arrival boundaries.
    ``scheduler_kw`` passes through to every replica's Scheduler.
    """

    def __init__(
        self,
        replicas: int = 2,
        journal_dir=None,
        clock: Callable[[], float] = time.monotonic,
        idle: Callable[[float], None] = time.sleep,
        lease_s: float = DEFAULT_LEASE_S,
        hedge_frac: float = DEFAULT_HEDGE_FRAC,
        faults: Optional[FaultPlan] = None,
        lease_store: Optional[LeaseStore] = None,
        store_grace_s: Optional[float] = None,
        **scheduler_kw,
    ):
        if replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        if journal_dir is None:
            raise ValueError(
                "journal_dir is required: the fleet's crash-safety story "
                "IS the per-replica journals"
            )
        os.makedirs(os.fspath(journal_dir), exist_ok=True)
        self.clock = clock
        self.idle = idle
        self.lease_s = lease_s
        self.hedge_frac = hedge_frac
        self.faults = faults if faults is not None else FaultPlan()
        # the pluggable lease store (module docstring): in-process by
        # default; its injected-latency stalls run through the router's
        # OWN idle so FakeClock tests stay honest
        self.authority: LeaseStore = (
            lease_store if lease_store is not None
            else FenceAuthority(clock=clock)
        )
        self.authority.on_delay = idle
        self.store_grace_s = (
            DEFAULT_STORE_GRACE_LEASES * lease_s
            if store_grace_s is None else store_grace_s
        )
        # rejoin needs to rebuild a Replica with the SAME scheduler
        # construction the fleet was born with
        self._scheduler_kw = dict(scheduler_kw)
        self.replicas: list[Replica] = [
            Replica(
                i,
                os.path.join(os.fspath(journal_dir), f"replica-{i}.journal"),
                self.authority,
                clock=clock,
                lease_s=lease_s,
                # ONE plan, fleet-wide: the router consults its
                # replica-level kinds, every scheduler the
                # request-addressed ones — so a nan/oom fault fires on
                # whichever replica hosts its victim, exactly once
                faults=self.faults,
                **scheduler_kw,
            )
            for i in range(replicas)
        ]
        # router-level terminal records: all-replicas-shed rejections
        # land here (replica results are harvested via collect())
        self.results: dict[str, ServeResult] = {}
        self._arrivals = 0
        self.handoffs = 0
        self.adopted_total = 0
        self.zombies: dict[int, Replica] = {}
        # the fleet-wide exactly-once ledger: every DELIVERED terminal
        # record's id (replica collect()s evict, so each record passes
        # harvest exactly once) — a second delivery for an id is the
        # double-completion bug class the fencing exists to prevent,
        # recorded here as hard evidence instead of being silently
        # last-writer-overwritten in the results dict
        self._delivered_ids: set[str] = set()
        self.double_delivered: list[str] = []
        # -- survivability state (module docstring) --
        # lease-store outage machine: when the outage started (None =
        # store healthy), how many admissions were refused past the
        # grace window (the backoff exponent), and the deaths whose
        # fence round-trip the outage deferred
        self._outage_since: Optional[float] = None
        self._outage_refusals = 0
        self._deferred_dead: list[tuple[Replica, str, bool]] = []
        # rejoin bookkeeping: when each replica id was last declared
        # dead, the rejoin-latency measurements armed by rejoin_replica
        # (observed at the first completed delivery from the rejoined
        # incarnation), and the observed shape mix that seeds the
        # rejoiner's warm pool
        self.rejoins = 0
        self._killed_at: dict[int, float] = {}
        self._rejoin_pending: dict[int, float] = {}
        self._shape_mix: dict[tuple, list] = {}
        # incarnations replaced by a rejoin: out of the routing set but
        # kept addressable — their journals' finished-id memory still
        # backs the duplicate gate, and their counters still feed the
        # fleet-wide accounting. Bounded (RETIRED_INCARNATIONS_KEPT):
        # evicted incarnations fold their counters into the aggregates
        # below so the accounting stays exact even when the duplicate-
        # gate memory of ancient epochs ages out
        self._retired: list[Replica] = []
        self._retired_drain_sheds = 0
        self._retired_starvation: tuple[dict, dict] = ({}, {})

    # -- liveness ------------------------------------------------------------

    def live_replicas(self) -> list[Replica]:
        return [r for r in self.replicas if r.live]

    def _admitting(self) -> list[Replica]:
        return [r for r in self.replicas if r.live and not r.draining]

    def _suspect(self, rep: Replica, now: float) -> bool:
        return rep.lease.remaining(now) < self.hedge_frac * self.lease_s

    def check_leases(self) -> list[int]:
        """Declare every lease-expired replica dead (fence, then hand
        off) under the router's clock. Returns the ids declared this
        call. The order is the fencing contract: the token is revoked
        BEFORE the journal replay starts, so there is no window in
        which the zombie and a survivor both own a request."""
        now = self.clock()
        declared = []
        for rep in self.replicas:
            if rep.live and rep.lease.expired(now):
                obs_trace.event(
                    "fleet:lease-expired",
                    replica=rep.replica_id,
                    overdue_s=round(now - rep.lease.deadline, 6),
                )
                obs_metrics.counter(
                    obs_metrics.LEASE_EXPIRY_TOTAL
                ).inc()
                self._declare_dead(rep, cause="lease-expired",
                                   zombie=True)
                declared.append(rep.replica_id)
        return declared

    def kill_replica(self, replica_id: int) -> None:
        """SIGKILL semantics: harvest what the dead replica already
        delivered (its journal compacted those), drop it, fence it,
        hand its journal off. The chaos drill's kill entry."""
        rep = self._by_id(replica_id)
        if rep is None or not rep.live:
            return
        obs_trace.event("fleet:replica-kill", replica=replica_id)
        self._declare_dead(rep, cause="killed", zombie=False)

    def _by_id(self, replica_id: int) -> Optional[Replica]:
        for rep in self.replicas:
            if rep.replica_id == replica_id:
                return rep
        return None

    def _declare_dead(self, rep: Replica, cause: str,
                      zombie: bool) -> None:
        # 1. harvest results the dead replica already delivered — the
        #    journal compacted them, so the handoff below cannot replay
        #    them; dropping them here would read as lost. Through the
        #    delivery LEDGER (_deliver), not a raw dict update: a
        #    record delivered here and again by a survivor is the
        #    double-completion evidence the ledger exists to keep
        for rid, res in rep.scheduler.collect().items():
            self._deliver(rid, res, rep.replica_id)
        self._killed_at[rep.replica_id] = self.clock()
        # 2. fence FIRST: from this instant the (possible) zombie's
        #    journal writes raise, so the survivors own the requests
        #    exclusively before any of them is re-admitted. The fence is
        #    a store ROUND-TRIP: during a lease-store outage it raises,
        #    and the death is DEFERRED — the replica stops being stepped
        #    (dead=True) but its fence+handoff wait for the store, so no
        #    survivor adopts work the un-fenced token could still
        #    complete (fail-safe: ownership never splits)
        try:
            self.authority.fence(rep.replica_id)
        except LeaseStoreError as exc:
            self._enter_outage(exc)
            rep.dead = True
            if zombie:
                self.zombies[rep.replica_id] = rep
            self._deferred_dead.append((rep, cause, zombie))
            obs_trace.event(
                "fleet:death-deferred",
                replica=rep.replica_id,
                cause=cause,
                deferred=len(self._deferred_dead),
            )
            return
        rep.dead = True
        if zombie:
            # the process object lives on (lease expiry, not SIGKILL):
            # keep it addressable for the resurrection drill
            self.zombies[rep.replica_id] = rep
        self._finish_death(rep, cause)

    def _finish_death(self, rep: Replica, cause: str) -> None:
        # 3. hand off the journal to the survivors — every LIVE replica
        #    is a candidate (handoff.py prefers non-draining ones but
        #    falls back to draining: already-acknowledged fleet work is
        #    not a new admission, and a draining replica finishes what
        #    it owns before exiting)
        survivors = [r for r in self.replicas if r.live]
        adopted, abandoned = handoff_journal(
            rep.journal_path, survivors, clock=self.clock,
            dead_replica=rep.replica_id,
        )
        if adopted > 0:
            # only a sweep that moved work counts: "handoffs >= 1"
            # gates must never be satisfiable by an empty or
            # abandoning no-op
            self.handoffs += 1
        self.adopted_total += adopted
        # the dead replica's gauges would otherwise freeze at their
        # last published values — phantom backlog on a replica that no
        # longer exists, contradicting the handoff that just moved it
        obs_metrics.replica_gauge(
            "fleet_queue_depth", rep.replica_id
        ).set(0)
        obs_metrics.replica_gauge(
            "fleet_in_flight", rep.replica_id
        ).set(0)
        obs_trace.event(
            "fleet:replica-dead",
            replica=rep.replica_id,
            cause=cause,
            adopted_by_survivors=adopted,
            abandoned=abandoned,
            survivors=[s.replica_id for s in survivors],
        )

    # -- replica-addressed fault injection -----------------------------------

    def _apply_replica_faults(self, arrival_index: int) -> None:
        """Fire replica faults whose 0-based ``at_request`` has landed:
        ``arrival_index`` is the request arriving NOW during a submit
        (so ``at_request=8`` fires exactly as ``chaos-0008`` arrives,
        before it is routed — matching the 0-based id scheme
        everywhere else), or the last-landed index between arrivals
        (router steps never fire a fault early)."""
        for fault in self.faults.faults:
            if (fault.fired
                    or (fault.kind not in REPLICA_KINDS
                        and fault.kind not in LEASE_STORE_KINDS)
                    or arrival_index < fault.at_request):
                continue
            fault.fired = True
            obs_trace.event(
                "fleet:fault", kind=fault.kind, replica=fault.replica,
                at_request=fault.at_request,
            )
            rep = self._by_id(fault.replica)
            if fault.kind == "replica_kill":
                self.kill_replica(fault.replica)
            elif fault.kind == "replica_hang" and rep is not None:
                rep.hung_until = self.clock() + fault.delay_s
            elif fault.kind == "lease_clock_skew" and rep is not None:
                rep.lease.skew_s = fault.skew_s
            elif fault.kind == "lease_store_outage":
                self.authority.fail_for(fault.delay_s)
                self._enter_outage(None)
            elif fault.kind == "lease_store_latency":
                self.authority.delay_for(fault.delay_s)

    # -- lease-store outage machine ------------------------------------------

    def _enter_outage(self, exc: Optional[BaseException]) -> None:
        if self._outage_since is not None:
            return
        self._outage_since = self.clock()
        self._outage_refusals = 0
        obs_trace.event(
            "fleet:lease-store-outage",
            grace_s=round(self.store_grace_s, 6),
            detail=None if exc is None else str(exc),
        )

    def _store_gate(self) -> None:
        """Probe the store once per boundary while an outage is on;
        the first answered ping runs recovery."""
        if self._outage_since is None:
            return
        try:
            self.authority.ping()
        except LeaseStoreError:
            return
        self._recover_store()

    def _recover_store(self) -> None:
        """The outage-exit protocol, in the order that keeps ownership
        single: (1) reload what the STORE says (``refresh`` — a
        file-backed store may have been advanced by another process),
        (2) re-validate every live replica's lease epoch against it —
        any replica the store no longer recognises is declared dead
        (fence now round-trips) BEFORE admission resumes, (3) complete
        the deferred deaths' fences and handoffs, (4) clear the outage
        state. Only then does ``submit`` stop refusing."""
        outage_s = self.clock() - (self._outage_since or 0.0)
        self.authority.refresh()
        revoked = [
            rep for rep in self.live_replicas()
            if not self.authority.valid(rep.replica_id, rep.token.epoch)
        ]
        for rep in revoked:
            self._declare_dead(
                rep, cause="lease-revoked-during-outage", zombie=True
            )
        deferred, self._deferred_dead = self._deferred_dead, []
        for rep, cause, zombie in deferred:
            self.authority.fence(rep.replica_id)
            self._finish_death(rep, cause)
        self._outage_since = None
        self._outage_refusals = 0
        obs_trace.event(
            "fleet:lease-store-recovered",
            outage_s=round(outage_s, 6),
            revalidated=len(self.live_replicas()),
            revoked=[r.replica_id for r in revoked],
            deferred_deaths=len(deferred),
        )

    def _refuse_past_grace(self) -> None:
        """The fail-safe admission stance: inside the grace window the
        fleet keeps admitting on unexpired leases; past it, every
        submit raises classified exit-9 backpressure whose hint backs
        off exponentially, capped (TPU014 — a client honouring the
        hints never hammers a down store, and never waits unboundedly
        either)."""
        if self._outage_since is None:
            return
        elapsed = self.clock() - self._outage_since
        if elapsed <= self.store_grace_s:
            return
        retry_after = min(
            self.lease_s * (2 ** self._outage_refusals),
            STORE_BACKOFF_CAP_LEASES * self.lease_s,
        )
        self._outage_refusals += 1
        obs_trace.event(
            "fleet:lease-store-reject",
            outage_s=round(elapsed, 6),
            retry_after_s=round(retry_after, 6),
        )
        raise FleetUnavailableError(
            "lease store unreachable past the grace window "
            f"({elapsed:.3f}s > {self.store_grace_s:.3f}s): admission "
            "is fail-safe during a coordination outage (resubmit after "
            "the hint; serving of already-admitted work continues)",
            retry_after_s=retry_after,
        )

    # -- admission -----------------------------------------------------------

    def submit(self, problem: Problem, deadline_s: float | None = None,
               max_retries: int | None = None,
               request_id: str | None = None,
               tenant: str = "default",
               priority: int = 1) -> Optional[ServeResult]:
        """Route one request (same surface as ``Scheduler.submit``).

        Returns ``None`` on acceptance, the terminal shed when EVERY
        live replica refused (minimum ``retry_after_s``), and raises
        :class:`FleetUnavailableError` when no replica can admit at
        all — or when a lease-store outage has outlived the grace
        window — loud, classified, never a hang."""
        self._apply_replica_faults(self._arrivals)
        self._arrivals += 1
        self._store_gate()
        self._refuse_past_grace()
        self.check_leases()
        now = self.clock()
        if request_id is not None and self._knows(request_id):
            # the scheduler's duplicate-id door, fleet-wide: one id, one
            # owner — a resubmission must not fork the request onto a
            # second replica (it would double-complete by construction)
            return ServeResult(
                request_id=request_id, outcome="shed",
                detail="duplicate-request-id",
            )
        candidates = self._admitting()
        if not candidates:
            raise FleetUnavailableError(
                "every fleet replica is dead or draining: no admission "
                "path remains (resubmit once a replica rejoins)",
                retry_after_s=self.lease_s,
            )
        key = warm_affinity_key(problem.M, problem.N, problem.norm)
        self._note_shape(key, problem)
        healthy = [r for r in candidates if not self._suspect(r, now)]
        hedged = healthy if healthy else candidates
        if healthy and len(healthy) < len(candidates):
            # at least one candidate was routed AROUND on suspicion —
            # the hedge: don't queue new work behind a probably-dying
            # replica that has not yet missed its deadline
            obs_trace.event(
                "fleet:hedge",
                suspected=[
                    r.replica_id for r in candidates if r not in healthy
                ],
            )
        order = sorted(hedged, key=lambda r: routing_load_key(r, key))
        # one concrete id per LOGICAL request, minted here when the
        # caller brought none: every candidate probe runs under it, so
        # a rejected probe's record can be erased by name and the
        # terminal all-shed below is recorded under a real id instead
        # of one phantom uuid per replica probed
        rid = request_id if request_id is not None else new_request_id()
        retry_hints = []
        for rep in order:
            shed = rep.scheduler.submit(
                problem, deadline_s=deadline_s, max_retries=max_retries,
                request_id=rid, tenant=tenant, priority=priority,
            )
            if shed is None:
                obs_trace.event(
                    "fleet:route",
                    replica=rep.replica_id,
                    warm=key in rep.warm_keys(),
                )
                return None
            if shed.outcome != "shed" or shed.detail == "duplicate-request-id":
                # a terminal classification (invalid geometry, duplicate
                # id) is the request's answer, not backpressure — it
                # must not be retried onto another replica
                return shed
            # the probe's rejection is the ROUTER's redirect, not this
            # replica's lifecycle event: erase the scheduler-side
            # record so harvest() can never merge a stale shed over the
            # completion another replica is about to deliver (nothing
            # was journaled or queued — sheds are rejected pre-durable)
            rep.scheduler.results.pop(rid, None)
            if shed.retry_after_s is not None:
                retry_hints.append(shed.retry_after_s)
        retry_after = min(retry_hints) if retry_hints else None
        result = ServeResult(
            request_id=rid,
            outcome="shed",
            detail="fleet-backpressure",
            retry_after_s=retry_after,
        )
        # the one authoritative terminal record of the rejection —
        # counted once fleet-wide, whoever minted the id
        self.results[rid] = result
        obs_trace.event(
            "fleet:shed-all-replicas",
            request_id=rid,
            retry_after_s=retry_after,
        )
        return result

    def _note_shape(self, key, problem: Problem) -> None:
        """Track the observed shape mix (affinity key → count + an
        exemplar problem): the rejoin handshake pre-warms a fresh
        incarnation from the most-observed buckets."""
        entry = self._shape_mix.get(key)
        if entry is None:
            self._shape_mix[key] = [1, problem]
        else:
            entry[0] += 1

    # -- rejoin ---------------------------------------------------------------

    def rejoin_replica(
        self, replica_id: int,
        prewarm_buckets: int = DEFAULT_PREWARM_BUCKETS,
    ) -> Replica:
        """Re-enter a dead (or zombie-fenced) replica as a FRESH
        incarnation — the rejoin ladder, in order:

        1. **fresh epoch** — the lease store :meth:`~.replica.LeaseStore.issue`
           round-trip mints the new incarnation's token (the old one
           stays fenced forever). During a store outage this raises and
           the rejoin is refused classified — a fleet that cannot reach
           its coordination service must not grow membership.
        2. **journal archive + replay** — the dead incarnation's ledger
           is renamed aside (``<journal>.e<old_epoch>``) and replayed
           through the handoff adoption path BEFORE the new incarnation
           is routable; anything a live owner already holds (or that
           was already delivered terminally) is skipped, so no request
           is ever co-owned across epochs. The new incarnation starts
           its own journal empty at the original path.
        3. **warm-pool pre-warm** — the rejoiner builds batch contexts
           for the router's most-observed shape buckets, so its first
           real requests land warm instead of paying cold compiles.
        4. **take traffic** — only now does the incarnation replace the
           dead one in the routing set (``fleet:rejoin`` event with the
           incarnation epoch pair).

        Returns the new :class:`~.replica.Replica`. The kill→first
        completed solve latency of the rejoined replica is observed
        into ``rejoin_latency_seconds`` at delivery time."""
        rep = self._by_id(replica_id)
        if rep is None:
            raise ValueError(f"no replica {replica_id} in this fleet")
        if rep.live:
            raise ValueError(
                f"replica {replica_id} is live: only a dead or fenced "
                "replica can rejoin (drain it or kill it first)"
            )
        self._store_gate()
        old_epoch = rep.token.epoch
        idx = self.replicas.index(rep)
        archive = None
        if os.path.exists(rep.journal_path):
            archive = f"{rep.journal_path}.e{old_epoch}"
            os.replace(rep.journal_path, archive)
        try:
            new_rep = Replica(
                replica_id,
                rep.journal_path,
                self.authority,
                clock=self.clock,
                lease_s=self.lease_s,
                faults=self.faults,
                **self._scheduler_kw,
            )
        except LeaseStoreError as exc:
            if archive is not None:
                # undo the archive: the dead incarnation's ledger stays
                # the durable truth until a rejoin actually happens
                os.replace(archive, rep.journal_path)
            self._enter_outage(exc)
            raise FleetUnavailableError(
                f"replica {replica_id} cannot rejoin during a "
                "lease-store outage: minting a fresh incarnation needs "
                "the store (retry after the hint)",
                retry_after_s=self.lease_s,
            ) from exc
        adopted = abandoned = 0
        if archive is not None:
            adopted, abandoned = handoff_journal(
                archive,
                [new_rep] + [
                    r for r in self.replicas if r.live and r is not rep
                ],
                clock=self.clock,
                dead_replica=replica_id,
                skip=self._owned_elsewhere(rep),
            )
            if adopted > 0:
                self.handoffs += 1
            self.adopted_total += adopted
        warmed = 0
        mix = sorted(
            self._shape_mix.items(),
            key=lambda kv: (-kv[1][0], repr(kv[0])),
        )
        for _key, (_count, problem) in mix[:prewarm_buckets]:
            new_rep.scheduler.prewarm(problem)
            warmed += 1
        # the old incarnation leaves the routing set only now — its
        # counters (drain sheds, starvation episodes) stay reachable
        # for the chaos report's accounting
        self._retired.append(rep)
        for old in self._retired[:-RETIRED_INCARNATIONS_KEPT]:
            self._retired_drain_sheds += old.scheduler.drain_sheds
            episodes, announced = self._retired_starvation
            for tenant, n in old.scheduler.queue.starvation_episodes.items():
                episodes[tenant] = episodes.get(tenant, 0) + n
            for tenant, n in old.scheduler.queue.starvation_announced.items():
                announced[tenant] = announced.get(tenant, 0) + n
        del self._retired[:-RETIRED_INCARNATIONS_KEPT]
        self.replicas[idx] = new_rep
        self.rejoins += 1
        killed_at = self._killed_at.get(replica_id)
        if killed_at is not None:
            self._rejoin_pending[replica_id] = killed_at
        obs_metrics.counter(obs_metrics.FLEET_REJOIN_TOTAL).inc()
        obs_trace.event(
            "fleet:rejoin",
            replica=replica_id,
            old_epoch=old_epoch,
            new_epoch=new_rep.token.epoch,
            adopted=adopted,
            abandoned=abandoned,
            prewarmed=warmed,
        )
        return new_rep

    def _owned_elsewhere(self, old_rep: Replica):
        """The rejoin replay's skip predicate: True when some LIVE
        replica owns the id, or it was already delivered terminally —
        re-adopting either would co-own a request across epochs. The
        old incarnation itself is excluded (its in-memory journal
        remembers everything it ever admitted, which would skip the
        whole archive)."""
        def skip(req) -> bool:
            rid = req.request_id
            if rid in self.results or rid in self._delivered_ids:
                return True
            return any(
                r.scheduler.owns_request(rid)
                for r in self.replicas
                if r is not old_rep and r.live
            )
        return skip

    # -- fleet-wide accounting (the chaos report reads these) ----------------

    def _all_incarnations(self) -> list[Replica]:
        out: list[Replica] = []
        for rep in [*self.replicas, *self.zombies.values(), *self._retired]:
            if all(rep is not seen for seen in out):
                out.append(rep)
        return out

    def drain_shed_total(self) -> int:
        """Redirect sheds issued by draining schedulers fleet-wide —
        every incarnation ever routed to, dead and retired included:
        those sheds are unrecorded by design (``Scheduler.begin_drain``)
        and this count is what keeps the chaos report's zero-lost
        accounting provable for a replica killed mid-drain."""
        return self._retired_drain_sheds + sum(
            rep.scheduler.drain_sheds for rep in self._all_incarnations()
        )

    def starvation_counts(self) -> tuple[dict, dict]:
        """Fleet-wide (episodes, announced) per tenant. Any tenant with
        episodes > announced starved SILENTLY — the chaos invariant
        violation."""
        folded_ep, folded_an = self._retired_starvation
        episodes: dict[str, int] = dict(folded_ep)
        announced: dict[str, int] = dict(folded_an)
        for rep in self._all_incarnations():
            q = rep.scheduler.queue
            for tenant, n in q.starvation_episodes.items():
                episodes[tenant] = episodes.get(tenant, 0) + n
            for tenant, n in q.starvation_announced.items():
                announced[tenant] = announced.get(tenant, 0) + n
        return episodes, announced

    def audit_ownership(self) -> list[str]:
        """Ids LIVE-owned by more than one live replica right now —
        the cross-epoch co-ownership violation. Must always be empty:
        fence-before-handoff and the rejoin skip predicate exist to
        keep it so; the chaos loop calls this at every boundary and
        accumulates any evidence."""
        owner: dict[str, int] = {}
        dups: set[str] = set()
        for rep in self.live_replicas():
            for rid in rep.scheduler.owned_live_ids():
                if rid in owner and owner[rid] != rep.replica_id:
                    dups.add(rid)
                owner[rid] = rep.replica_id
        return sorted(dups)

    def _knows(self, request_id: str) -> bool:
        """Fleet-wide ownership of an id — DEAD replicas included: a
        since-killed replica's in-memory journal still remembers what
        it finished (its on-disk snapshot compacted the ids away), and
        that memory is what stops an ordinary client retry of an
        already-delivered request from double-completing on a survivor.
        A recorded fleet-backpressure shed that never dispatched is NOT
        ownership (the outcome table's safe-to-resubmit promise — the
        scheduler-level carve-out, applied at the router's door too)."""
        prior = self.results.get(request_id)
        if (prior is not None and prior.outcome == "shed"
                and not prior.dispatched):
            del self.results[request_id]
        elif prior is not None:
            return True
        return any(
            rep.scheduler.owns_request(request_id)
            for rep in self._all_incarnations()
        )

    # -- the fleet loop ------------------------------------------------------

    def step(self) -> bool:
        """One boundary across the fleet: fire due replica faults,
        check leases (dead replicas fence + hand off), advance every
        live replica, publish per-replica gauges. Returns True while
        any replica still holds work.

        Lease renewals happen in a SWEEP after all stepping: in this
        in-process simulation the replicas run sequentially, so a slow
        boundary on one (a fresh bucket's compile) must not eat into a
        peer's lease window — the sweep stamps every live, non-hung
        replica at the same instant, exactly as concurrent heartbeats
        would. A hung replica skips the sweep, which is what lets its
        lease expire while the process lives (the zombie drill)."""
        self._apply_replica_faults(self._arrivals - 1)
        self._store_gate()
        self.check_leases()
        working = False
        for rep in self.live_replicas():
            working = rep.step() or working
            rep.publish_metrics()
        now = self.clock()
        for rep in self.live_replicas():
            if not rep.hung(now):
                rep.lease.renew()
        return working

    def drain(self, max_steps: int = 100_000) -> dict[str, ServeResult]:
        """Step until every admitted request is terminal fleet-wide.

        A fleet left with work but zero live replicas raises
        ``FleetUnavailableError`` — the classified exit-9 contract —
        instead of spinning on a queue nobody owns."""
        steps = 0
        while True:
            working = self.step()
            self.harvest()
            if not working and not any(
                r.queue_depth() or r.in_flight()
                for r in self.live_replicas()
            ):
                if self._deferred_dead and self._pending_anywhere():
                    # deferred deaths hold journaled work hostage until
                    # the store answers the fence: idle in lease
                    # fractions and keep probing (step's _store_gate).
                    # An injected outage is finite; a permanently dead
                    # store lands on the classified max_steps backstop
                    steps += 1
                    if steps > max_steps:
                        raise FleetUnavailableError(
                            "lease store outage outlived the drain: "
                            "deferred handoffs could never complete "
                            "(exit 9)",
                            retry_after_s=self.lease_s,
                        )
                    self.idle(self.lease_s / 10)
                    continue
                if not self.live_replicas() and self._pending_anywhere():
                    raise FleetUnavailableError(
                        "every replica died with requests still "
                        "admitted: nothing can drain them (exit 9)"
                    )
                return dict(self.results)
            if working and not any(
                r.in_flight() for r in self.live_replicas()
            ):
                # only backoff-parked retries remain: wait the soonest
                # one out instead of spinning (Scheduler.drain's idle
                # contract, fleet-wide)
                now = self.clock()
                waits = [
                    w
                    for rep in self.live_replicas()
                    for w in (rep.scheduler.queue.next_ready_in(now),)
                    if w is not None
                ]
                if waits:
                    self.idle(min(waits))
            elif not working:
                # the only replicas holding work are HUNG (a stepped
                # scheduler with work reports working): nothing to do
                # but let their leases run down — idle in lease
                # fractions instead of hot-spinning into max_steps
                # before the expiry can even land (the TPU014 stance)
                self.idle(self.lease_s / 10)
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"fleet drain exceeded {max_steps} steps"
                )

    def _pending_anywhere(self) -> bool:
        return any(
            r.queue_depth() or r.in_flight() for r in self.replicas
        )

    def harvest(self) -> dict[str, ServeResult]:
        """Merge every replica's delivered results (the zombies'
        PRE-fence ones included — their journals compacted those) into
        the router's buffer; returns the buffer.

        Each scheduler ``collect()`` EVICTS, so every terminal record
        passes through the ledger (``_deliver``) exactly once — which
        makes a second delivery for an id the double-completion bug
        class itself, not a merge artifact: it is appended to
        ``double_delivered`` (the chaos report's zero-double evidence)
        and trace-evented, never silently last-writer-overwritten."""
        for rep in self.replicas:
            for rid, res in rep.scheduler.collect().items():
                self._deliver(rid, res, rep.replica_id)
        return self.results

    def _deliver(self, rid: str, res: ServeResult,
                 replica_id: int) -> None:
        """The fleet's exactly-once delivery ledger: EVERY terminal
        record a replica hands up (steady-state harvest AND the
        declare-dead sweep) passes here once."""
        if rid in self._delivered_ids:
            self.double_delivered.append(rid)
            # windowed bound: evidence of a bug, not a log
            del self.double_delivered[:-1024]
            obs_trace.event(
                "fleet:double-delivery", request_id=rid,
                replica=replica_id, outcome=res.outcome,
            )
        self._delivered_ids.add(rid)
        self.results[rid] = res
        if (res.outcome == "completed"
                and replica_id in self._rejoin_pending):
            # the rejoin-latency contract: kill → FIRST completed solve
            # delivered by the rejoined incarnation
            latency = self.clock() - self._rejoin_pending.pop(replica_id)
            obs_metrics.histogram(
                obs_metrics.REJOIN_LATENCY_SECONDS
            ).observe(latency)
            obs_trace.event(
                "fleet:rejoin-first-solve", replica=replica_id,
                latency_s=round(latency, 6),
            )

    def collect(self) -> dict[str, ServeResult]:
        """Hand off and evict the merged results (the
        ``Scheduler.collect`` contract, fleet-wide)."""
        self.harvest()
        out = self.results
        self.results = {}
        return out

    # -- drain / shutdown ----------------------------------------------------

    def shutdown(self) -> dict[str, ServeResult]:
        """Graceful fleet drain: every replica stops admitting, finishes
        what it owns, and the merged results come back — the SIGTERM
        path. New submissions during shutdown shed with
        ``retry_after_s`` (or raise exit 9 once every replica drains to
        a stop)."""
        for rep in self.live_replicas():
            rep.begin_drain()
        obs_trace.event(
            "fleet:drain",
            replicas=[r.replica_id for r in self.live_replicas()],
        )
        return self.drain()
