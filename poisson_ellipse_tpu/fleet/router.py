"""Shape-aware, health-checked routing over N scheduler replicas.

The router is the fleet's front door and its failure detector in one
loop — the crash-safety ladder, in the order a request experiences it:

1. **Route warm** — admission prefers a live replica already holding the
   request's compile bucket (``runtime.compile_cache.warm_affinity_key``
   == the scheduler's batch-context key), then least-loaded. A request
   lands where its executable is warm; only a cold bucket pays a
   compile, and only once per fleet, not once per replica.
2. **Honor backpressure** — a replica that sheds (queue full, draining,
   infeasible deadline) answers with ``retry_after_s``; the router
   tries the next candidate and only returns a terminal shed (with the
   MINIMUM retry hint — the soonest anyone frees up) when every live
   replica refused.
3. **Hedge around suspects** — a replica whose lease is inside the
   hedge margin of expiry is *suspected*: new requests route around it
   (``fleet:hedge`` trace event) rather than queue behind a process
   that is probably dying. Suspicion is cheap and reversible; death is
   neither, so the thresholds differ.
4. **Declare, fence, hand off** — a replica that misses its lease
   deadline is declared dead under the ROUTER's monotonic clock: its
   fencing token is revoked FIRST (zombie writes now rejected), then
   its journal replays into the survivors (``fleet.handoff``) with
   remaining-deadline budgets preserved. Queued and in-flight requests
   re-enter exactly once; completed ones were compacted and do not.
5. **Classify total loss** — with zero live, non-draining replicas the
   router raises ``FleetUnavailableError`` (exit 9, carrying a
   ``retry_after_s`` hint) instead of hanging a request on a queue
   nobody will drain. One replica down is routine; all replicas down is
   loud.

Drain (``shutdown()``) is the graceful inverse: every replica stops
admitting (``Scheduler.begin_drain``), finishes what it owns, flushes
metrics — the SIGTERM path of ``harness serve``/``harness fleet`` rides
this hook.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from poisson_ellipse_tpu.fleet.handoff import handoff_journal
from poisson_ellipse_tpu.fleet.replica import (
    DEFAULT_LEASE_S,
    FenceAuthority,
    Replica,
    routing_load_key,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.resilience.errors import FleetUnavailableError
from poisson_ellipse_tpu.resilience.faultinject import (
    REPLICA_KINDS,
    FaultPlan,
)
from poisson_ellipse_tpu.runtime.compile_cache import warm_affinity_key
from poisson_ellipse_tpu.serve.request import (
    ServeRequest,
    ServeResult,
    new_request_id,
)

# fraction of the lease length left below which a replica is SUSPECTED
# (new requests hedge around it); 0 disables hedging
DEFAULT_HEDGE_FRAC = 0.25


class FleetRouter:
    """N replicas behind one admission surface (see module docstring).

    ``journal_dir`` holds one ledger per replica
    (``replica-<i>.journal``); ``clock`` must be monotonic (injectable
    for deterministic lease tests); ``faults`` takes replica-addressed
    injections (``faultinject.replica_kill/replica_hang/
    lease_clock_skew``) consulted at arrival boundaries.
    ``scheduler_kw`` passes through to every replica's Scheduler.
    """

    def __init__(
        self,
        replicas: int = 2,
        journal_dir=None,
        clock: Callable[[], float] = time.monotonic,
        idle: Callable[[float], None] = time.sleep,
        lease_s: float = DEFAULT_LEASE_S,
        hedge_frac: float = DEFAULT_HEDGE_FRAC,
        faults: Optional[FaultPlan] = None,
        **scheduler_kw,
    ):
        import os

        if replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        if journal_dir is None:
            raise ValueError(
                "journal_dir is required: the fleet's crash-safety story "
                "IS the per-replica journals"
            )
        os.makedirs(os.fspath(journal_dir), exist_ok=True)
        self.clock = clock
        self.idle = idle
        self.lease_s = lease_s
        self.hedge_frac = hedge_frac
        self.faults = faults if faults is not None else FaultPlan()
        self.authority = FenceAuthority()
        self.replicas: list[Replica] = [
            Replica(
                i,
                os.path.join(os.fspath(journal_dir), f"replica-{i}.journal"),
                self.authority,
                clock=clock,
                lease_s=lease_s,
                # ONE plan, fleet-wide: the router consults its
                # replica-level kinds, every scheduler the
                # request-addressed ones — so a nan/oom fault fires on
                # whichever replica hosts its victim, exactly once
                faults=self.faults,
                **scheduler_kw,
            )
            for i in range(replicas)
        ]
        # router-level terminal records: all-replicas-shed rejections
        # land here (replica results are harvested via collect())
        self.results: dict[str, ServeResult] = {}
        self._arrivals = 0
        self.handoffs = 0
        self.adopted_total = 0
        self.zombies: dict[int, Replica] = {}
        # the fleet-wide exactly-once ledger: every DELIVERED terminal
        # record's id (replica collect()s evict, so each record passes
        # harvest exactly once) — a second delivery for an id is the
        # double-completion bug class the fencing exists to prevent,
        # recorded here as hard evidence instead of being silently
        # last-writer-overwritten in the results dict
        self._delivered_ids: set[str] = set()
        self.double_delivered: list[str] = []

    # -- liveness ------------------------------------------------------------

    def live_replicas(self) -> list[Replica]:
        return [r for r in self.replicas if r.live]

    def _admitting(self) -> list[Replica]:
        return [r for r in self.replicas if r.live and not r.draining]

    def _suspect(self, rep: Replica, now: float) -> bool:
        return rep.lease.remaining(now) < self.hedge_frac * self.lease_s

    def check_leases(self) -> list[int]:
        """Declare every lease-expired replica dead (fence, then hand
        off) under the router's clock. Returns the ids declared this
        call. The order is the fencing contract: the token is revoked
        BEFORE the journal replay starts, so there is no window in
        which the zombie and a survivor both own a request."""
        now = self.clock()
        declared = []
        for rep in self.replicas:
            if rep.live and rep.lease.expired(now):
                obs_trace.event(
                    "fleet:lease-expired",
                    replica=rep.replica_id,
                    overdue_s=round(now - rep.lease.deadline, 6),
                )
                obs_metrics.counter(
                    obs_metrics.LEASE_EXPIRY_TOTAL
                ).inc()
                self._declare_dead(rep, cause="lease-expired",
                                   zombie=True)
                declared.append(rep.replica_id)
        return declared

    def kill_replica(self, replica_id: int) -> None:
        """SIGKILL semantics: harvest what the dead replica already
        delivered (its journal compacted those), drop it, fence it,
        hand its journal off. The chaos drill's kill entry."""
        rep = self._by_id(replica_id)
        if rep is None or not rep.live:
            return
        obs_trace.event("fleet:replica-kill", replica=replica_id)
        self._declare_dead(rep, cause="killed", zombie=False)

    def _by_id(self, replica_id: int) -> Optional[Replica]:
        for rep in self.replicas:
            if rep.replica_id == replica_id:
                return rep
        return None

    def _declare_dead(self, rep: Replica, cause: str,
                      zombie: bool) -> None:
        # 1. harvest results the dead replica already delivered — the
        #    journal compacted them, so the handoff below cannot replay
        #    them; dropping them here would read as lost. Through the
        #    delivery LEDGER (_deliver), not a raw dict update: a
        #    record delivered here and again by a survivor is the
        #    double-completion evidence the ledger exists to keep
        for rid, res in rep.scheduler.collect().items():
            self._deliver(rid, res, rep.replica_id)
        # 2. fence FIRST: from this instant the (possible) zombie's
        #    journal writes raise, so the survivors own the requests
        #    exclusively before any of them is re-admitted
        self.authority.fence(rep.replica_id)
        rep.dead = True
        if zombie:
            # the process object lives on (lease expiry, not SIGKILL):
            # keep it addressable for the resurrection drill
            self.zombies[rep.replica_id] = rep
        # 3. hand off the journal to the survivors — every LIVE replica
        #    is a candidate (handoff.py prefers non-draining ones but
        #    falls back to draining: already-acknowledged fleet work is
        #    not a new admission, and a draining replica finishes what
        #    it owns before exiting)
        survivors = [r for r in self.replicas if r.live]
        adopted, abandoned = handoff_journal(
            rep.journal_path, survivors, clock=self.clock,
            dead_replica=rep.replica_id,
        )
        if adopted > 0:
            # only a sweep that moved work counts: "handoffs >= 1"
            # gates must never be satisfiable by an empty or
            # abandoning no-op
            self.handoffs += 1
        self.adopted_total += adopted
        # the dead replica's gauges would otherwise freeze at their
        # last published values — phantom backlog on a replica that no
        # longer exists, contradicting the handoff that just moved it
        obs_metrics.replica_gauge(
            "fleet_queue_depth", rep.replica_id
        ).set(0)
        obs_metrics.replica_gauge(
            "fleet_in_flight", rep.replica_id
        ).set(0)
        obs_trace.event(
            "fleet:replica-dead",
            replica=rep.replica_id,
            cause=cause,
            adopted_by_survivors=adopted,
            abandoned=abandoned,
            survivors=[s.replica_id for s in survivors],
        )

    # -- replica-addressed fault injection -----------------------------------

    def _apply_replica_faults(self, arrival_index: int) -> None:
        """Fire replica faults whose 0-based ``at_request`` has landed:
        ``arrival_index`` is the request arriving NOW during a submit
        (so ``at_request=8`` fires exactly as ``chaos-0008`` arrives,
        before it is routed — matching the 0-based id scheme
        everywhere else), or the last-landed index between arrivals
        (router steps never fire a fault early)."""
        for fault in self.faults.faults:
            if (fault.fired or fault.kind not in REPLICA_KINDS
                    or arrival_index < fault.at_request):
                continue
            fault.fired = True
            obs_trace.event(
                "fleet:fault", kind=fault.kind, replica=fault.replica,
                at_request=fault.at_request,
            )
            rep = self._by_id(fault.replica)
            if fault.kind == "replica_kill":
                self.kill_replica(fault.replica)
            elif fault.kind == "replica_hang" and rep is not None:
                rep.hung_until = self.clock() + fault.delay_s
            elif fault.kind == "lease_clock_skew" and rep is not None:
                rep.lease.skew_s = fault.skew_s

    # -- admission -----------------------------------------------------------

    def submit(self, problem: Problem, deadline_s: float | None = None,
               max_retries: int | None = None,
               request_id: str | None = None) -> Optional[ServeResult]:
        """Route one request (same surface as ``Scheduler.submit``).

        Returns ``None`` on acceptance, the terminal shed when EVERY
        live replica refused (minimum ``retry_after_s``), and raises
        :class:`FleetUnavailableError` when no replica can admit at
        all — loud, classified, never a hang."""
        self._apply_replica_faults(self._arrivals)
        self._arrivals += 1
        self.check_leases()
        now = self.clock()
        if request_id is not None and self._knows(request_id):
            # the scheduler's duplicate-id door, fleet-wide: one id, one
            # owner — a resubmission must not fork the request onto a
            # second replica (it would double-complete by construction)
            return ServeResult(
                request_id=request_id, outcome="shed",
                detail="duplicate-request-id",
            )
        candidates = self._admitting()
        if not candidates:
            raise FleetUnavailableError(
                "every fleet replica is dead or draining: no admission "
                "path remains (resubmit once a replica rejoins)",
                retry_after_s=self.lease_s,
            )
        key = warm_affinity_key(problem.M, problem.N, problem.norm)
        healthy = [r for r in candidates if not self._suspect(r, now)]
        hedged = healthy if healthy else candidates
        if healthy and len(healthy) < len(candidates):
            # at least one candidate was routed AROUND on suspicion —
            # the hedge: don't queue new work behind a probably-dying
            # replica that has not yet missed its deadline
            obs_trace.event(
                "fleet:hedge",
                suspected=[
                    r.replica_id for r in candidates if r not in healthy
                ],
            )
        order = sorted(hedged, key=lambda r: routing_load_key(r, key))
        # one concrete id per LOGICAL request, minted here when the
        # caller brought none: every candidate probe runs under it, so
        # a rejected probe's record can be erased by name and the
        # terminal all-shed below is recorded under a real id instead
        # of one phantom uuid per replica probed
        rid = request_id if request_id is not None else new_request_id()
        retry_hints = []
        for rep in order:
            shed = rep.scheduler.submit(
                problem, deadline_s=deadline_s, max_retries=max_retries,
                request_id=rid,
            )
            if shed is None:
                obs_trace.event(
                    "fleet:route",
                    replica=rep.replica_id,
                    warm=key in rep.warm_keys(),
                )
                return None
            if shed.outcome != "shed" or shed.detail == "duplicate-request-id":
                # a terminal classification (invalid geometry, duplicate
                # id) is the request's answer, not backpressure — it
                # must not be retried onto another replica
                return shed
            # the probe's rejection is the ROUTER's redirect, not this
            # replica's lifecycle event: erase the scheduler-side
            # record so harvest() can never merge a stale shed over the
            # completion another replica is about to deliver (nothing
            # was journaled or queued — sheds are rejected pre-durable)
            rep.scheduler.results.pop(rid, None)
            if shed.retry_after_s is not None:
                retry_hints.append(shed.retry_after_s)
        retry_after = min(retry_hints) if retry_hints else None
        result = ServeResult(
            request_id=rid,
            outcome="shed",
            detail="fleet-backpressure",
            retry_after_s=retry_after,
        )
        # the one authoritative terminal record of the rejection —
        # counted once fleet-wide, whoever minted the id
        self.results[rid] = result
        obs_trace.event(
            "fleet:shed-all-replicas",
            request_id=rid,
            retry_after_s=retry_after,
        )
        return result

    def _knows(self, request_id: str) -> bool:
        """Fleet-wide ownership of an id — DEAD replicas included: a
        since-killed replica's in-memory journal still remembers what
        it finished (its on-disk snapshot compacted the ids away), and
        that memory is what stops an ordinary client retry of an
        already-delivered request from double-completing on a survivor.
        A recorded fleet-backpressure shed that never dispatched is NOT
        ownership (the outcome table's safe-to-resubmit promise — the
        scheduler-level carve-out, applied at the router's door too)."""
        prior = self.results.get(request_id)
        if (prior is not None and prior.outcome == "shed"
                and not prior.dispatched):
            del self.results[request_id]
        elif prior is not None:
            return True
        return any(
            rep.scheduler.owns_request(request_id)
            for rep in self.replicas
        )

    # -- the fleet loop ------------------------------------------------------

    def step(self) -> bool:
        """One boundary across the fleet: fire due replica faults,
        check leases (dead replicas fence + hand off), advance every
        live replica, publish per-replica gauges. Returns True while
        any replica still holds work.

        Lease renewals happen in a SWEEP after all stepping: in this
        in-process simulation the replicas run sequentially, so a slow
        boundary on one (a fresh bucket's compile) must not eat into a
        peer's lease window — the sweep stamps every live, non-hung
        replica at the same instant, exactly as concurrent heartbeats
        would. A hung replica skips the sweep, which is what lets its
        lease expire while the process lives (the zombie drill)."""
        self._apply_replica_faults(self._arrivals - 1)
        self.check_leases()
        working = False
        for rep in self.live_replicas():
            working = rep.step() or working
            rep.publish_metrics()
        now = self.clock()
        for rep in self.live_replicas():
            if not rep.hung(now):
                rep.lease.renew()
        return working

    def drain(self, max_steps: int = 100_000) -> dict[str, ServeResult]:
        """Step until every admitted request is terminal fleet-wide.

        A fleet left with work but zero live replicas raises
        ``FleetUnavailableError`` — the classified exit-9 contract —
        instead of spinning on a queue nobody owns."""
        steps = 0
        while True:
            working = self.step()
            self.harvest()
            if not working and not any(
                r.queue_depth() or r.in_flight()
                for r in self.live_replicas()
            ):
                if not self.live_replicas() and self._pending_anywhere():
                    raise FleetUnavailableError(
                        "every replica died with requests still "
                        "admitted: nothing can drain them (exit 9)"
                    )
                return dict(self.results)
            if working and not any(
                r.in_flight() for r in self.live_replicas()
            ):
                # only backoff-parked retries remain: wait the soonest
                # one out instead of spinning (Scheduler.drain's idle
                # contract, fleet-wide)
                now = self.clock()
                waits = [
                    w
                    for rep in self.live_replicas()
                    for w in (rep.scheduler.queue.next_ready_in(now),)
                    if w is not None
                ]
                if waits:
                    self.idle(min(waits))
            elif not working:
                # the only replicas holding work are HUNG (a stepped
                # scheduler with work reports working): nothing to do
                # but let their leases run down — idle in lease
                # fractions instead of hot-spinning into max_steps
                # before the expiry can even land (the TPU014 stance)
                self.idle(self.lease_s / 10)
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"fleet drain exceeded {max_steps} steps"
                )

    def _pending_anywhere(self) -> bool:
        return any(
            r.queue_depth() or r.in_flight() for r in self.replicas
        )

    def harvest(self) -> dict[str, ServeResult]:
        """Merge every replica's delivered results (the zombies'
        PRE-fence ones included — their journals compacted those) into
        the router's buffer; returns the buffer.

        Each scheduler ``collect()`` EVICTS, so every terminal record
        passes through the ledger (``_deliver``) exactly once — which
        makes a second delivery for an id the double-completion bug
        class itself, not a merge artifact: it is appended to
        ``double_delivered`` (the chaos report's zero-double evidence)
        and trace-evented, never silently last-writer-overwritten."""
        for rep in self.replicas:
            for rid, res in rep.scheduler.collect().items():
                self._deliver(rid, res, rep.replica_id)
        return self.results

    def _deliver(self, rid: str, res: ServeResult,
                 replica_id: int) -> None:
        """The fleet's exactly-once delivery ledger: EVERY terminal
        record a replica hands up (steady-state harvest AND the
        declare-dead sweep) passes here once."""
        if rid in self._delivered_ids:
            self.double_delivered.append(rid)
            # windowed bound: evidence of a bug, not a log
            del self.double_delivered[:-1024]
            obs_trace.event(
                "fleet:double-delivery", request_id=rid,
                replica=replica_id, outcome=res.outcome,
            )
        self._delivered_ids.add(rid)
        self.results[rid] = res

    def collect(self) -> dict[str, ServeResult]:
        """Hand off and evict the merged results (the
        ``Scheduler.collect`` contract, fleet-wide)."""
        self.harvest()
        out = self.results
        self.results = {}
        return out

    # -- drain / shutdown ----------------------------------------------------

    def shutdown(self) -> dict[str, ServeResult]:
        """Graceful fleet drain: every replica stops admitting, finishes
        what it owns, and the merged results come back — the SIGTERM
        path. New submissions during shutdown shed with
        ``retry_after_s`` (or raise exit 9 once every replica drains to
        a stop)."""
        for rep in self.live_replicas():
            rep.begin_drain()
        obs_trace.event(
            "fleet:drain",
            replicas=[r.replica_id for r in self.live_replicas()],
        )
        return self.drain()
