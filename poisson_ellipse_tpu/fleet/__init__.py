"""fleet — the replicated serving layer (ISSUE 12).

Crash-safety promoted from per-process to fleet-wide: N ``serve``
scheduler replicas behind a shape-aware, health-checked router, with
journal-backed request handoff when a replica dies and lease fencing so
a zombie can never double-complete. Three legs:

- :mod:`.replica` — one ``Scheduler`` + its own crash-safe journal
  under a **lease** (monotonic-clock heartbeat renewed at chunk
  boundaries) and a **fencing token** (every journal write validates
  the epoch; stale writes raise :class:`~.replica.StaleLeaseError`,
  trace-evented and counted).
- :mod:`.router` — routing by compile-bucket affinity (the
  ``runtime.compile_cache`` warm-pool key: requests land where their
  executable is already warm), per-replica backpressure honored with
  fleet-minimum ``retry_after_s``, hedging around suspect leases,
  graceful drain, and the classified
  ``resilience.errors.FleetUnavailableError`` (exit 9) only when ALL
  replicas are down.
- :mod:`.handoff` — a dead replica's journal replayed into survivors'
  admission: remaining-deadline budgets preserved, backlog waves
  reused from the single-process replay, zero-lost/zero-double pinned
  by the fencing order (revoke first, replay second).

The chaos invariants (zero lost / zero double / all classified) extend
across replica kill, kill-during-handoff and zombie resurrection —
``serve.chaos.run_chaos(replicas=…)``, ``harness fleet``, and
``tests/test_fleet.py`` all pin them.

The survivability layer (ISSUE 19) makes membership elastic and the
coordination service a fault domain: ``FleetRouter.rejoin_replica``
re-enters a dead replica as a fresh incarnation (archived-journal
replay through the adoption path, warm-pool pre-warm, no cross-epoch
co-ownership); the :class:`~.replica.LeaseStore` surface (in-process
:class:`~.replica.FenceAuthority` default, file-backed
:class:`~.replica.FileLeaseStore`) is injectable with outage/latency
faults and the fleet degrades fail-safe behind a grace window; and
multi-tenant admission classes (``ServeRequest.tenant``/``priority``)
get per-class quotas, priority preemption and loud starvation events.
"""

from poisson_ellipse_tpu.fleet.handoff import handoff_journal
from poisson_ellipse_tpu.fleet.replica import (
    DEFAULT_LEASE_S,
    FenceAuthority,
    FencingToken,
    FileLeaseStore,
    Lease,
    LeaseStore,
    Replica,
    StaleLeaseError,
)
from poisson_ellipse_tpu.fleet.router import DEFAULT_HEDGE_FRAC, FleetRouter

__all__ = [
    "DEFAULT_HEDGE_FRAC",
    "DEFAULT_LEASE_S",
    "FenceAuthority",
    "FencingToken",
    "FileLeaseStore",
    "FleetRouter",
    "Lease",
    "LeaseStore",
    "Replica",
    "StaleLeaseError",
    "handoff_journal",
]
