"""Journal-backed request handoff: a dead replica's work moves, not dies.

The crash-safe journal (PR 7, ``serve.journal``) made a single process
restartable: admitted-but-unfinished requests replay into the SAME
scheduler after a kill. The fleet promotes exactly that machinery one
level: when a replica is declared dead (lease missed, SIGKILL, fenced
zombie), its on-disk journal — the durable truth, reopened fresh the
way a restart would — is replayed into the SURVIVORS' admission instead.

The invariants the replay preserves:

- **remaining-deadline budget** — the journal stores
  ``deadline_left_s`` (remaining seconds at admission), and
  ``ServeRequest.from_spec`` restarts that budget from the handoff
  clock: a request admitted with 60 s to live is adopted with its
  budget intact, exactly as a same-process replay would grant it (the
  PR 7 contract, unchanged by crossing a replica boundary).
- **zero lost** — adoption is journal-first (``Scheduler.adopt_request``
  writes the survivor's ledger BEFORE queueing), and capacity overflow
  goes to the survivor's replay-backlog waves, never a terminal shed —
  so a second kill mid-handoff finds every adopted request durably
  owned by someone and hands it off again.
- **zero double** — the dead replica's token was fenced BEFORE this
  replay started (``fleet.router`` orders it so), which closes both
  races: a zombie completing a request the survivor now owns is
  rejected at its journal, and a request the dead replica already
  finished was compacted out of its snapshot and is simply not here to
  replay.

Handoff latency (journal open → last adoption) is measured per handoff
(``handoff_latency_seconds`` histogram) — it is the fleet's
recovery-time story, and ``bench.py``'s fleet key reports its p99.
"""

from __future__ import annotations

import time

from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.serve.journal import RequestJournal


def handoff_journal(journal_path, survivors, clock=time.monotonic,
                    dead_replica: int | None = None,
                    skip=None) -> tuple[int, int]:
    """Replay a dead replica's journal into ``survivors``' admission.

    ``journal_path`` is reopened from disk — SIGKILL semantics: whatever
    the dead process held in memory is gone, the ledger is the truth.
    ``survivors`` is an ordered list of live :class:`~.replica.Replica`
    objects (the router passes them affinity-sorted per request).
    Returns ``(adopted, abandoned)``. Only a sweep that ADOPTED work
    counts as a handoff in the metrics — an empty journal's or an
    abandoning sweep's latency sample would pull the recovery-time p99
    toward zero, and "handoffs >= 1" gates must not be satisfiable by
    a no-op.

    Adoption is CLASS-AWARE: unfinished requests replay
    highest-priority first (earliest deadline within a class), so a
    dying replica's important work is re-owned before recovery spends
    time on batch work — under a second failure mid-handoff, what got
    adopted is what mattered most.

    ``skip`` (a predicate on the rebuilt request) drops entries some
    LIVE owner already holds — the REJOIN handshake passes it, because
    a journal archived at death time still lists requests the death
    handoff moved to survivors, and re-adopting those would co-own a
    request across epochs. Skipped entries count as neither adopted
    nor abandoned (they are owned elsewhere, not lost).
    """
    t0 = clock()
    now = clock()
    ledger = RequestJournal(journal_path)
    reqs = sorted(
        ledger.unfinished(now),
        key=lambda r: (
            -r.priority,
            r.deadline if r.deadline is not None else float("inf"),
        ),
    )
    if skip is not None:
        reqs = [r for r in reqs if not skip(r)]
    adopted = 0
    abandoned = 0
    for req in reqs:
        target = _pick_survivor(survivors, req)
        if target is None:
            # no LIVE survivor at all: the requests stay in the dead
            # ledger (and the dead scheduler's queue), which is what
            # makes the router's drain classify the total loss as
            # exit 9 instead of returning a result set missing them —
            # and the abandonment is loud, never a silent truncation
            abandoned = len(reqs) - adopted
            obs_trace.event(
                "fleet:handoff-abandoned",
                from_replica=dead_replica,
                abandoned=abandoned,
            )
            break
        target.scheduler.adopt_request(req)
        adopted += 1
        obs_trace.event(
            "fleet:handoff",
            request_id=req.request_id,
            from_replica=dead_replica,
            to_replica=target.replica_id,
            deadline_left_s=(
                None if req.deadline is None
                else round(req.deadline - now, 6)
            ),
        )
    latency = clock() - t0
    if adopted > 0:
        # only a sweep that MOVED work is a handoff: an empty journal's
        # ~µs sweep would dilute the recovery-time p99 toward zero and
        # let "handoffs >= 1" gates pass on a recovery of nothing
        obs_metrics.counter(obs_metrics.FLEET_HANDOFF_TOTAL).inc()
        obs_metrics.histogram(
            obs_metrics.HANDOFF_LATENCY_SECONDS
        ).observe(latency)
    obs_metrics.counter(
        obs_metrics.FLEET_HANDOFF_REQUESTS_TOTAL
    ).inc(adopted)
    obs_trace.event(
        "fleet:handoff-done",
        from_replica=dead_replica,
        adopted=adopted,
        abandoned=abandoned,
        unfinished=len(reqs),
        latency_s=round(latency, 6),
    )
    return adopted, abandoned


def _pick_survivor(survivors, req):
    """The adoption target: the router's shared routing order
    (``replica.routing_load_key`` — free lanes, then warm affinity,
    then load) applied to the handoff path, so recovery traffic neither
    cold-starts the idle replica nor buries the warm one. A DRAINING
    survivor is a last resort, not a refusal: drain's "stop admitting"
    covers new client work, while a handed-off request is
    already-acknowledged fleet work — parking it on a draining replica
    (which finishes everything it owns before exiting) preserves
    zero-lost through a shutdown that races a death."""
    from poisson_ellipse_tpu.fleet.replica import routing_load_key
    from poisson_ellipse_tpu.runtime.compile_cache import warm_affinity_key

    candidates = [s for s in survivors if s.live and not s.draining]
    if not candidates:
        candidates = [s for s in survivors if s.live]
    if not candidates:
        return None
    key = warm_affinity_key(req.problem.M, req.problem.N, req.problem.norm)
    return min(candidates, key=lambda s: routing_load_key(s, key))
